"""SLO-driven replica autoscaling with scale-to-zero and guardrails.

ROADMAP item 4 closes here: the fleet saturation rollup (PR 7,
``server/fleet.py``) and the per-model SLO burn state (PR 8) were built
as the autoscaler's signal sources — this leader-only loop finally
consumes them. Per-model bounds live on the Model
(``autoscale_min``/``autoscale_max``; max 0 = off). Decisions use ONLY
existing signals:

- **occupancy** (running/slots) and **queue wait** from the shared
  READY-worker scrape (``scrape_normalized_samples`` — the same
  pipeline ``GET /v2/debug/fleet`` serves, so dashboards and decisions
  can't disagree);
- **SLO burn state**: a FIRING queue-wait/TTFT burn on the model is an
  immediate scale-up signal;
- **traffic**: per-model request-count deltas from the live request
  histogram drive the idle clock for scale-to-zero.

Flap damping: scale-down needs the low-occupancy condition to HOLD for
``autoscale_down_stable_s`` (hysteresis), and every action starts a
per-model ``autoscale_cooldown_s`` cooldown (wake-from-zero exempt —
cold start already costs enough). Scale-to-zero releases a min-0
model's replicas after ``autoscale_idle_after_s`` of zero traffic and
zero in-flight; the first request for the scaled-to-zero model (the
proxy's 503 path calls :meth:`note_demand` AND persists the durable
``Model.wake_requested_at`` marker, so in HA a request landing on a
follower still wakes the model — the leader consumes and clears the
marker each pass) wakes it, and the measured
cold start — SCHEDULED→RUNNING dwell p95 from the PR 5 lifecycle
histogram — is exported so operators can judge whether scale-to-zero
is affordable for a model.

Hard guardrails: never scale down past in-flight load (running +
waiting vs slot capacity), never act while a rollout for the model is
mid-flight, and **freeze** — trace event + ``gpustack_autoscale_frozen``
metric, no replica writes — when the newest scrape for a model with
running replicas is older than ``autoscale_stale_after_s``. Degraded
telemetry fails safe instead of thrashing replicas.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional

from gpustack_tpu.config import Config
from gpustack_tpu.observability.metrics import (
    METRIC_FAMILIES,
    escape_label_value,
    get_registry,
)
from gpustack_tpu.schemas import (
    Model,
    ModelInstance,
    ModelInstanceState,
    Rollout,
    Worker,
    WorkerState,
)
from gpustack_tpu.schemas.rollouts import ACTIVE_ROLLOUT_STATES
from gpustack_tpu.server.collectors import DirtyTrackedTask
from gpustack_tpu.utils.profiling import timed

logger = logging.getLogger(__name__)

QUEUE_WAIT_METRIC = "gpustack_tpu:queue_oldest_wait_seconds"
SCRAPE_AGE_METRIC = "gpustack_tpu:scrape_age_seconds"

# lifecycle states whose dwell adds up to a cold start (instance
# creation → serving); labels of gpustack_instance_state_seconds
COLD_START_STATES = ("scheduled", "downloading", "starting")

DECISION_HISTORY = 128

# minimum seconds between durable wake-marker writes from the proxy's
# 503 path (routes/openai_proxy.py) — client retries through a cold
# start must not become one Model write per request
WAKE_MARKER_REFRESH_S = 5.0


@dataclasses.dataclass
class ModelSignals:
    """Per-model slice of the fleet scrape the decision loop reads."""

    occupancy: Optional[float] = None
    queue_wait_s: Optional[float] = None
    requests_running: float = 0.0
    requests_waiting: float = 0.0
    slots_total: float = 0.0
    # cumulative prompt+generation tokens across the model's engines —
    # ground-truth traffic no matter WHICH server proxied the request
    # (in HA the leader's request histogram never sees follower-served
    # traffic, but the engines' own counters do)
    tokens_total: float = 0.0
    # seconds since the stalest replica's engine scrape; None = the
    # scrape returned no samples for this model at all
    age_s: Optional[float] = None


@dataclasses.dataclass
class _ModelState:
    name: str = ""
    last_action_at: float = 0.0
    low_since: Optional[float] = None
    last_traffic_at: float = 0.0
    last_request_count: float = -1.0
    last_engine_tokens: float = -1.0
    frozen: bool = False
    last_action: str = ""
    target: int = -1


class Autoscaler(DirtyTrackedTask):
    dirty_kinds = ("model", "model_instance", "rollout")
    task_name = "autoscaler"

    def __init__(self, app, cfg: Config, signals=None):
        super().__init__(max(0.05, cfg.autoscale_interval))
        self.app = app
        self.cfg = cfg
        # injectable signal provider (tests feed synthetic fleets);
        # the default reads the shared READY-worker scrape
        self._signals = signals or self._fleet_signals
        self._state: Dict[int, _ModelState] = {}
        self._wake: set = set()          # model names with 503'd demand
        self._decisions: deque = deque(maxlen=DECISION_HISTORY)
        self._events = get_registry("server").counter(
            "gpustack_autoscale_events_total",
            label_names=("model", "action"),
        )
        self.ticks = 0
        # dirty-set skip (DirtyTrackedTask): with NO autoscale-enabled
        # model and nothing dirty since the last pass, the tick skips
        # its Model/Instance/Rollout scans entirely; with autoscale
        # models present, the Model list is still read every tick (the
        # durable wake marker is a set_field write and deliberately
        # publishes no bus event) but the big instance/rollout scans
        # reuse the cached snapshot while nothing is dirty
        self._no_autoscale = False
        self._inst_cache = None

    async def tick(self) -> None:
        await self.scale_once()

    # ---- wake hook (called from the proxy's 503 path) --------------------

    def note_demand(self, model_name: str) -> None:
        """A request arrived for a model with no running replicas —
        remember it so the next tick can wake a scaled-to-zero model."""
        self._wake.add(model_name)

    # ---- decision loop ---------------------------------------------------

    @timed(threshold_s=5.0, name="autoscaler.scale")
    async def scale_once(
        self, now: Optional[float] = None
    ) -> List[Dict[str, Any]]:
        """One decision pass; ``now`` is injectable (sloeval-style) so
        tests drive cooldowns and idle clocks deterministically.
        Returns the decisions applied this pass."""
        now = time.time() if now is None else now
        self.ticks += 1
        changed = self._drain_dirty()
        if not changed and self._no_autoscale and not self._wake:
            # steady-state no-op: no model opted into autoscaling
            # last pass and nothing was written since — zero
            # Model/Instance list queries this tick
            self.skipped_ticks += 1
            return []
        try:
            models = await Model.filter(limit=None)
            scaled = [m for m in models if m.autoscale_max > 0]
            self._no_autoscale = not scaled
            if not scaled:
                self._state.clear()
                # demand notes for non-autoscaled models must not pool
                self._wake.clear()
                return []
            if changed or self._inst_cache is None:
                instances = await ModelInstance.filter(limit=None)
                rollouts = await Rollout.filter(limit=None)
                self._inst_cache = (instances, rollouts)
        except Exception:
            # the drained dirtiness was consumed but nothing acted on
            # it — re-arm or the next tick could skip pending work
            self._rearm_dirty()
            raise
        # on a clean pass the cached snapshot is exact (any write —
        # ours included — re-arms a fresh read above)
        instances, rollouts = self._inst_cache
        by_model: Dict[int, List[ModelInstance]] = {}
        for inst in instances:
            by_model.setdefault(inst.model_id, []).append(inst)
        mid_rollout = {
            r.model_id for r in rollouts
            if r.state in ACTIVE_ROLLOUT_STATES
        }
        # snapshot-and-swap: demand noted WHILE this pass runs (the
        # fleet scrape awaits) must survive to the next tick, not be
        # cleared unhandled at the end
        wake, self._wake = self._wake, set()
        # wake demand for a scaled-to-zero model is the ONLY record of
        # a waiting client: track it as pending BEFORE any durable
        # marker is cleared, and re-pool whatever a replica write
        # never served in the finally — a skipped decision (freeze,
        # rollout exclusion, changed-under-us), a pass that dies
        # mid-scrape, or an exception in the consume loop itself must
        # not evaporate the demand
        pending = {
            m.name for m in scaled
            if m.name in wake and max(0, m.replicas) == 0
        }
        applied: List[Dict[str, Any]] = []
        live_ids = set()
        try:
            # durable wake markers (HA): a request that 503'd on a
            # FOLLOWER lands in Model.wake_requested_at
            # (routes/openai_proxy.py) — the leader's in-memory
            # note_demand set never sees follower traffic.
            # Consume-and-clear so a handled marker can't replay as a
            # phantom wake after a later scale-to-zero; pending is
            # updated BEFORE each clear so a mid-loop failure keeps
            # already-cleared markers' demand alive.
            for m in scaled:
                if m.wake_requested_at > 0:
                    wake.add(m.name)
                    if max(0, m.replicas) == 0:
                        pending.add(m.name)
                    # column-targeted clear: a whole-document write
                    # from this (already stale) snapshot could revert
                    # an operator PATCH landing between the filter and
                    # here
                    await Model.set_field(
                        m.id, "wake_requested_at", 0.0
                    )
            signals = await self._signals(scaled, instances)
            traffic = self._request_counts({m.name for m in scaled})
            for model in scaled:
                live_ids.add(model.id)
                try:
                    decision = await self._decide(
                        model,
                        by_model.get(model.id, []),
                        signals.get(model.name, ModelSignals()),
                        traffic.get(model.name, 0.0),
                        model.id in mid_rollout,
                        wake,
                        now,
                    )
                except Exception:
                    # one model's broken decision must not starve the
                    # rest (mirrors the rollout reconcile loop); its
                    # pending wake stays pooled for the next tick
                    logger.exception(
                        "autoscale decision failed for model %s",
                        model.name,
                    )
                    continue
                if decision is not None:
                    applied.append(decision)
                    # at zero the only possible actions write
                    # replicas >= 1, so the demand is served
                    pending.discard(model.name)
        finally:
            self._wake |= pending
        # deleted / autoscale-disabled models retire their state
        for mid in [m for m in self._state if m not in live_ids]:
            del self._state[mid]
        return applied

    async def _decide(
        self,
        model: Model,
        insts: List[ModelInstance],
        sig: ModelSignals,
        request_count: float,
        rollout_active: bool,
        wake: set,
        now: float,
    ) -> Optional[Dict[str, Any]]:
        st = self._state.setdefault(model.id, _ModelState())
        st.name = model.name
        lo = max(0, model.autoscale_min)
        hi = max(lo, model.autoscale_max)
        # Disaggregated models scale their DECODE role only (decode
        # capacity is the throughput dimension; prefill sizing is the
        # operator's long-context lever) — and never to zero, because
        # decode_replicas == 0 would flip the model out of
        # disaggregated mode entirely. The scaled field is what the
        # guarded write below targets.
        field = "decode_replicas" if model.disaggregated else "replicas"
        if model.disaggregated:
            lo = max(1, lo)
        current = max(0, getattr(model, field))
        st.target = current

        # traffic clock: any new proxied request resets the idle timer
        if st.last_request_count < 0:
            st.last_request_count = request_count
            st.last_traffic_at = now
        elif request_count > st.last_request_count:
            st.last_request_count = request_count
            st.last_traffic_at = now
        # engine-observed traffic also resets the clock: the request
        # histogram above is leader-local, so in HA a model served
        # entirely through a follower would look idle here and get
        # reaped by to_zero mid-use. The engines' scraped in-flight
        # gauges and cumulative token counters see every request
        # regardless of which server proxied it.
        if sig.requests_running + sig.requests_waiting > 0:
            st.last_traffic_at = now
        if (
            st.last_engine_tokens < 0
            or sig.tokens_total < st.last_engine_tokens
        ):
            # first sight, or counter went backwards (engine restart /
            # scaled to zero) — rebaseline without claiming traffic
            st.last_engine_tokens = sig.tokens_total
        elif sig.tokens_total > st.last_engine_tokens:
            st.last_engine_tokens = sig.tokens_total
            st.last_traffic_at = now
        if model.name in wake:
            # 503'd demand IS traffic. The proxy's 503 never reaches the
            # per-model request histogram (the trace has no resolved
            # target), so without this a cold start longer than the
            # cooldown flaps forever: wake → idle clock still stale →
            # to_zero reaps the warming replica → client retries → wake.
            st.last_traffic_at = now

        if rollout_active:
            # mutual exclusion: a rollout owns the replica set — a
            # concurrent resize would race its surge/drain arithmetic
            st.last_action = "skip_rollout"
            return None

        running = [
            i for i in insts if i.state == ModelInstanceState.RUNNING
        ]
        # ---- fail-safe freeze on stale signals ------------------------
        stale = bool(running) and (
            sig.age_s is None
            or sig.age_s > self.cfg.autoscale_stale_after_s
        )
        if stale:
            if not st.frozen:
                st.frozen = True
                self._events.inc(model=model.name, action="freeze")
                self._trace_freeze(model.name, sig, now)
                logger.warning(
                    "autoscaler frozen for model %s: fleet signals "
                    "stale (age %s)", model.name,
                    f"{sig.age_s:.1f}s" if sig.age_s is not None
                    else "no samples",
                )
            st.last_action = "freeze"
            # the hysteresis clock must not accrue while telemetry is
            # untrusted — otherwise the first unfrozen tick could
            # scale down on "stability" nobody actually observed
            st.low_since = None
            return None
        st.frozen = False

        in_flight = sig.requests_running + sig.requests_waiting
        slots_per_replica = max(1, model.max_slots)
        min_for_load = math.ceil(in_flight / slots_per_replica)
        cooled = now - st.last_action_at >= self.cfg.autoscale_cooldown_s

        target, action = current, ""
        slo_pressure = self._slo_pressure(model.name)
        hot = (
            (
                sig.occupancy is not None
                and sig.occupancy >= self.cfg.autoscale_up_occupancy
            )
            or (
                sig.queue_wait_s is not None
                and sig.queue_wait_s >= self.cfg.autoscale_queue_wait_s
            )
            or slo_pressure
        )
        cold = (
            sig.occupancy is not None
            and sig.occupancy <= self.cfg.autoscale_down_occupancy
            and (
                sig.queue_wait_s is None
                or sig.queue_wait_s
                < self.cfg.autoscale_queue_wait_s
            )
        )
        if cold and current > lo:
            if st.low_since is None:
                st.low_since = now
        else:
            st.low_since = None

        if current < lo:
            target, action = lo, "bounds"
        elif current > hi:
            # hard bound: autoscale_max wins even over in-flight load
            # (the operator lowered it deliberately; the invariant is
            # replicas-within-bounds)
            target, action = hi, "bounds"
        elif current == 0:
            if model.name in wake:
                # wake-from-zero skips the cooldown: the client is
                # already waiting out the cold start
                target, action = max(1, lo), "wake"
        elif hot:
            if current < hi and cooled:
                target, action = current + 1, "up"
        elif (
            lo == 0
            and in_flight <= 0
            and now - st.last_traffic_at
            >= self.cfg.autoscale_idle_after_s
            and cooled
        ):
            target, action = 0, "to_zero"
        elif (
            st.low_since is not None
            and now - st.low_since >= self.cfg.autoscale_down_stable_s
            and cooled
        ):
            # guardrail: never scale down past in-flight load. Floor
            # is at least 1: the 1 -> 0 step belongs exclusively to
            # the to_zero branch above, which alone checks the idle
            # clock and zero in-flight — 5s of low occupancy must not
            # park a model that served a request seconds ago
            target = max(
                current - 1, lo, 1, min(min_for_load, hi)
            )
            if target < current:
                action = "down"

        if not action or target == current:
            # st.target keeps `current`: the scale-down guardrail can
            # compute min_for_load > current, and exporting that as
            # "the target the autoscaler last wrote" would show a
            # phantom divergence on the target-vs-instances panel
            st.last_action = st.last_action or ""
            return None
        # fresh read for the decision basis, CAS for the write: this
        # pass awaited worker scrapes since `model` was read, and the
        # decision above assumed `model.replicas`. The pre-CAS version
        # re-fetched AND hoped nothing moved before its write; now the
        # write itself is guarded (Record.save, PR 10) with retries
        # OFF — any concurrent move (operator PATCH, rollout restore,
        # an HA peer) surfaces as ConflictError and this model simply
        # re-decides next tick on fresh state.
        from gpustack_tpu.orm.record import ConflictError

        fresh = await Model.get(model.id)
        if fresh is None or getattr(fresh, field) != getattr(
            model, field
        ):
            # compare the RAW snapshot, not the 0-clamped `current`: a
            # (client-writable) negative replica count would otherwise
            # mismatch forever and silently wedge bounds/wake
            return None  # changed under us; re-decide next tick
        try:
            await fresh.update(_retries=0, **{field: target})
        except ConflictError:
            return None  # changed under us; re-decide next tick
        # exported target tracks WRITES only — set after the
        # changed-under-us guard, or a skipped write would still
        # report the unapplied target on /metrics
        st.target = target
        st.last_action_at = now
        st.last_action = action
        st.low_since = None
        self._events.inc(model=model.name, action=action)
        decision = {
            "at": now,
            "model": model.name,
            "action": action,
            "from": current,
            "to": target,
            "occupancy": sig.occupancy,
            "queue_wait_s": sig.queue_wait_s,
            "in_flight": in_flight,
            "slo_pressure": slo_pressure,
        }
        self._decisions.append(decision)
        logger.info(
            "autoscaler: model %s %s %d -> %d (occ=%s wait=%s "
            "in_flight=%.0f slo=%s)",
            model.name, action, current, target,
            sig.occupancy, sig.queue_wait_s, in_flight, slo_pressure,
        )
        return decision

    # ---- signal collection -----------------------------------------------

    async def _fleet_signals(
        self, models: List[Model], instances: List[ModelInstance]
    ) -> Dict[str, ModelSignals]:
        """Default provider: the shared READY-worker metrics scrape
        (server/fleet.py — identical samples to /v2/debug/fleet)."""
        from gpustack_tpu.server.fleet import scrape_normalized_samples

        workers = [
            w for w in await Worker.filter(limit=None)
            if w.state == WorkerState.READY
        ]
        inst_model = {str(i.id): i.model_name for i in instances}
        workers_out, samples = await scrape_normalized_samples(
            self.app, workers, inst_model
        )
        dark = {
            wid for wid, info in workers_out.items()
            if not info.get("reachable")
        }
        out: Dict[str, ModelSignals] = {}
        for (model, _iid), metrics in samples.items():
            if not model:
                continue
            sig = out.setdefault(model, ModelSignals())
            sig.requests_running += metrics.get(
                "gpustack_tpu:requests_running", 0.0
            )
            sig.requests_waiting += metrics.get(
                "gpustack_tpu:requests_waiting", 0.0
            )
            sig.slots_total += metrics.get(
                "gpustack_tpu:slots_total", 0.0
            )
            sig.tokens_total += metrics.get(
                "gpustack_tpu:prompt_tokens_total", 0.0
            ) + metrics.get(
                "gpustack_tpu:generation_tokens_total", 0.0
            )
            wait = metrics.get(QUEUE_WAIT_METRIC)
            if wait is not None:
                sig.queue_wait_s = max(
                    sig.queue_wait_s or 0.0, wait
                )
            age = metrics.get(SCRAPE_AGE_METRIC)
            if age is not None:
                # worst replica: decisions must not ride one fresh
                # replica while another's telemetry went dark
                sig.age_s = max(sig.age_s or 0.0, age)
            elif sig.age_s is None:
                sig.age_s = 0.0
        # a replica whose WORKER scrape failed contributes no sample at
        # all — if a sibling replica still reports, the model would
        # read fresh (age from the sibling) with the dark replica's
        # load simply invisible, and 'cold' could scale down mid-
        # partition. Force worst-replica staleness so the freeze
        # guardrail catches partially-dark fleets too.
        if dark:
            for inst in instances:
                if (
                    inst.state == ModelInstanceState.RUNNING
                    and inst.worker_id in dark
                ):
                    sig = out.setdefault(
                        inst.model_name, ModelSignals()
                    )
                    sig.age_s = float("inf")
        for sig in out.values():
            if sig.slots_total > 0:
                sig.occupancy = min(
                    1.0, sig.requests_running / sig.slots_total
                )
        return out

    def _request_counts(self, names: set) -> Dict[str, float]:
        """Cumulative proxied-request counts per model (phase=total)
        from the live request histogram — the idle clock's input."""
        snap = get_registry("server").histogram(
            "gpustack_request_duration_seconds",
            label_names=("phase", "model", "outcome"),
        ).snapshot()
        out: Dict[str, float] = {}
        for (phase, model, _outcome), (_cum, _sum, count) in snap.items():
            if phase == "total" and model in names:
                out[model] = out.get(model, 0.0) + count
        return out

    def _slo_pressure(self, model_name: str) -> bool:
        """FIRING latency-shaped burn (queue_wait/ttft) = scale-up
        pressure; error-rate/availability burns are not capacity
        signals and never trigger growth."""
        evaluator = self.app.get("slo")
        if evaluator is None:
            return False
        firing = evaluator.engine.firing_objectives(model_name)
        return bool({"queue_wait", "ttft"} & set(firing))

    def _trace_freeze(
        self, model_name: str, sig: ModelSignals, now: float
    ) -> None:
        """Stale-signal freezes are operator-relevant: drop a trace
        entry in the server ring so /v2/debug/traces shows WHEN the
        autoscaler stopped trusting its inputs."""
        from gpustack_tpu.observability import tracing

        tracing.get_store("server").add({
            "trace_id": uuid.uuid4().hex,
            "span_id": uuid.uuid4().hex[:16],
            "component": "server",
            "name": "autoscaler.freeze",
            "model": model_name,
            "started_at": now,
            "duration_ms": 0.0,
            "outcome": "frozen",
            "events": [{
                "name": "signals_stale",
                "age_s": sig.age_s,
                "threshold_s": self.cfg.autoscale_stale_after_s,
            }],
        })

    # ---- reads -----------------------------------------------------------

    def cold_start_estimate(self) -> Optional[float]:
        """Measured cold start: p95 dwell of the pre-serving lifecycle
        states (SCHEDULED→RUNNING path) from the PR 5 state-dwell
        histogram. None until enough lifecycle history exists."""
        from gpustack_tpu.observability.metrics import DWELL_BUCKETS

        hist = get_registry("server").histogram(
            "gpustack_instance_state_seconds",
            buckets=DWELL_BUCKETS,
            label_names=("state",),
        )
        total, seen = 0.0, False
        for state in COLD_START_STATES:
            q = hist.quantile(0.95, state=state)
            if q is not None:
                total += q
                seen = True
        return total if seen else None

    def status(self) -> Dict[str, Any]:
        """Autoscaler view for ``GET /v2/debug/fleet``."""
        models = {}
        for _mid, st in sorted(self._state.items()):
            models[st.name or str(_mid)] = {
                "target": st.target,
                "frozen": st.frozen,
                "last_action": st.last_action,
                "last_action_at": st.last_action_at or None,
                "idle_seconds": (
                    round(time.time() - st.last_traffic_at, 1)
                    if st.last_traffic_at else None
                ),
            }
        return {
            "ticks": self.ticks,
            "interval_seconds": self.interval,
            "cold_start_p95_seconds": self.cold_start_estimate(),
            "models": models,
            "decisions": list(self._decisions)[-20:],
        }

    def metrics_lines(self) -> List[str]:
        """``gpustack_autoscale_*`` gauges (the decision counter
        renders via the shared registry). Rendered from the live state
        map — model ids resolve lazily through the decision loop's
        last pass."""
        target: List[str] = []
        frozen: List[str] = []
        lines: List[str] = []
        for _mid, st in sorted(self._state.items()):
            if not st.name:
                continue
            labels = f'model="{escape_label_value(st.name)}"'
            if st.target >= 0:
                target.append(
                    "gpustack_autoscale_replicas_target"
                    f"{{{labels}}} {st.target}"
                )
            frozen.append(
                "gpustack_autoscale_frozen"
                f"{{{labels}}} {1 if st.frozen else 0}"
            )

        def family(name: str, out: List[str]) -> List[str]:
            if not out:
                return []
            return [f"# TYPE {name} {METRIC_FAMILIES[name]}"] + out

        lines = family(
            "gpustack_autoscale_replicas_target", target
        ) + family("gpustack_autoscale_frozen", frozen)
        cold = self.cold_start_estimate()
        if cold is not None:
            kind = METRIC_FAMILIES[
                "gpustack_autoscale_cold_start_seconds"
            ]
            lines += [
                "# TYPE gpustack_autoscale_cold_start_seconds "
                f"{kind}",
                f"gpustack_autoscale_cold_start_seconds {cold:.3f}",
            ]
        return lines
