"""Tenant QoS: per-key quotas, token budgets, weighted-fair admission.

The reference meters every consumer at its gateway (Higress token-usage
plugin + ``ModelUsageMiddleware``, SURVEY §5); PRs 8–9 built our
metering half. This module is the *enforcement* half: with millions of
users behind one OpenAI surface, a single flooding tenant must get
**their own** 429s (and their own burn alert) — never the fleet's.

A **tenant** is one credential: an API key (``key:<id>``), a session
user (``user:<id>``), or a worker/system principal. API keys carry the
enforceable service class (``schemas/users.py`` ApiKey: weight,
priority, rate/concurrency quotas, rolling token budget); everything
else inherits the config defaults.

One :class:`TenancyRegistry` per server app makes one
:meth:`~TenancyRegistry.admit` decision per inference request, in
order:

1. **concurrency** — the tenant's own in-flight cap;
2. **request rate** — a clock-injected token bucket (``burst`` instant,
   ``rps`` sustained);
3. **token budget** — a rolling window fed by the PR 8 usage counters
   (prompt+completion tokens recorded per response); exhaustion is a
   429 with a machine-readable reason and a window-end ``Retry-After``;
4. **weighted-fair admission** — layered onto the per-model
   outstanding/shed path (``server/resilience.py``): once a model's
   in-flight total crosses the fair watermark, each tenant may hold at
   most its weight-proportional share of the model's admission slots
   (computed among active tenants of the same-or-higher priority, so
   the lowest priority sheds first); at the hard ceiling everything
   sheds. A tenant's admitted share of a saturated model therefore
   converges to its weight — the invariant the noisy-neighbor chaos
   class asserts.

Every path is pure and clock-injected (``clock=time.monotonic`` +
explicit ``now`` arguments) so the fairness math unit-tests without a
proxy. Per-tenant state is LRU-bounded (``tenant_state_max``) — tens
of thousands of synthetic tenants must not grow memory without bound
(the slow-suite scale test drives exactly that).
"""

from __future__ import annotations

import collections
import dataclasses
import math
import time
from typing import Dict, List, Optional, Tuple

# shed reasons (machine-readable: the 429 body carries them verbatim)
REASON_RATE = "rate_limit_exceeded"
REASON_CONCURRENCY = "concurrency_limit_exceeded"
REASON_BUDGET = "token_budget_exhausted"
REASON_FAIR = "fair_share_exceeded"
REASON_SATURATED = "model_saturated"

SHED_REASONS = (
    REASON_RATE, REASON_CONCURRENCY, REASON_BUDGET,
    REASON_FAIR, REASON_SATURATED,
)


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's enforceable service class (from its ApiKey record,
    or the config defaults for session/worker principals)."""

    tenant: str = ""              # stable id: key:<id> | user:<id> | …
    display: str = ""             # operator-facing name (key name)
    weight: int = 1               # fair-share weight (>= 1)
    priority: int = 0             # higher sheds later
    rate_rps: float = 0.0         # sustained requests/second; 0 = off
    burst: int = 0                # bucket capacity; 0 = derived
    max_concurrency: int = 0      # tenant-wide in-flight cap; 0 = off
    token_budget: int = 0         # tokens per window; 0 = off
    budget_window_s: float = 0.0  # 0 = registry default

    def bucket_capacity(self) -> float:
        if self.rate_rps <= 0:
            return 0.0
        if self.burst > 0:
            return float(self.burst)
        # default burst: one second of sustained rate, floor 1 — a
        # 0.5 rps tenant must still be able to send one request
        return max(1.0, self.rate_rps)


@dataclasses.dataclass
class Decision:
    """Outcome of one admission check. ``headers`` always carries the
    applicable ``X-RateLimit-*`` set (and ``Retry-After`` on a shed);
    ``owns_model_cap`` tells the proxy the weighted-fair layer governed
    this model, so the blind per-model shed must not double-judge."""

    admitted: bool
    tenant: str
    reason: str = ""
    retry_after: float = 0.0
    headers: Dict[str, str] = dataclasses.field(default_factory=dict)
    owns_model_cap: bool = False


class TokenBucket:
    """Request-rate limiter: ``capacity`` instant burst, ``rate``/s
    sustained refill. Pure against an injected ``now``."""

    __slots__ = ("rate", "capacity", "tokens", "stamped")

    def __init__(self, rate: float, capacity: float, now: float):
        self.rate = rate
        self.capacity = capacity
        self.tokens = capacity
        self.stamped = now

    def reconfigure(self, rate: float, capacity: float) -> None:
        if rate == self.rate and capacity == self.capacity:
            return
        if capacity > self.capacity:
            # a RAISED quota takes effect now: grant the new burst
            # headroom instead of making the tenant refill a bucket
            # sized for the old limit (operator raises a throttled
            # tenant's rps → their very next request must admit)
            self.tokens += capacity - self.capacity
        self.rate = rate
        self.capacity = capacity
        self.tokens = min(self.tokens, capacity)

    def _refill(self, now: float) -> None:
        dt = max(0.0, now - self.stamped)
        self.stamped = now
        self.tokens = min(self.capacity, self.tokens + dt * self.rate)

    def take(self, now: float) -> bool:
        self._refill(now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def remaining(self, now: float) -> int:
        self._refill(now)
        return int(self.tokens)

    def seconds_until_token(self, now: float) -> float:
        self._refill(now)
        if self.tokens >= 1.0:
            return 0.0
        if self.rate <= 0:
            return math.inf
        return (1.0 - self.tokens) / self.rate


class RollingBudget:
    """Token budget over a rolling window: the window opens at the
    first spend and rolls over (spend resets) at the boundary — the
    reference's per-consumer quota cycle, clock-injected."""

    __slots__ = ("window", "window_start", "spent")

    def __init__(self, window: float):
        self.window = max(1.0, window)
        self.window_start = 0.0
        self.spent = 0

    def _roll(self, now: float) -> None:
        if self.window_start == 0.0:
            self.window_start = now
            return
        if now - self.window_start >= self.window:
            # skip whole elapsed windows so an idle tenant's next
            # window starts aligned with its own traffic, not 1970
            elapsed = now - self.window_start
            self.window_start += math.floor(
                elapsed / self.window
            ) * self.window
            self.spent = 0

    def record(self, tokens: int, now: float) -> None:
        self._roll(now)
        self.spent += max(0, int(tokens))

    def remaining(self, limit: int, now: float) -> int:
        self._roll(now)
        return max(0, limit - self.spent)

    def seconds_until_reset(self, now: float) -> float:
        self._roll(now)
        if self.window_start == 0.0:
            return 0.0
        return max(0.0, self.window_start + self.window - now)


class _TenantState:
    __slots__ = (
        "spec", "bucket", "budget", "inflight", "per_model",
        "admitted_total", "shed_total", "shed_by_reason",
        "tokens_total", "last_seen", "named", "rehydrated",
    )

    def __init__(self, spec: TenantSpec, now: float):
        self.spec = spec
        self.bucket: Optional[TokenBucket] = None
        self.budget: Optional[RollingBudget] = None
        self.inflight = 0
        self.per_model: Dict[str, int] = {}
        self.admitted_total = 0
        self.shed_total = 0
        self.shed_by_reason: Dict[str, int] = {}
        self.tokens_total = 0
        self.last_seen = now
        # exported as its own /metrics series (sticky: assigned at
        # creation while named slots are free, never re-ranked — a
        # tenant moving between the named set and the "_other" rollup
        # would make the rollup counter non-monotonic)
        self.named = False
        # budget seeded from durable usage rows (once per state; see
        # TenancyRegistry.ensure_rehydrated)
        self.rehydrated = False


class _Lease:
    """Handle for one admitted request: release exactly once (the
    proxy's finally block), idempotent against double release."""

    __slots__ = ("_registry", "tenant", "model", "_done")

    def __init__(self, registry: "TenancyRegistry", tenant: str,
                 model: str):
        self._registry = registry
        self.tenant = tenant
        self.model = model
        self._done = False

    def release(self) -> None:
        if not self._done:
            self._done = True
            self._registry._end(self.tenant, self.model)


async def durable_budget_spend(tenant: str, window_s: float):
    """The default rehydrator: windowed SUM over durable
    ``model_usage`` rows for one tenant — the same rows that are
    billing truth for ``/v2/usage/summary``, so enforcement and
    metering agree across restarts. Returns ``(spent_tokens,
    age_of_oldest_row_s)`` or None when the tenant has no in-window
    history (or no Record binding exists — bare unit mounts)."""
    import datetime

    from gpustack_tpu.orm.record import Record

    try:
        db = Record.db()
    except AssertionError:
        return None
    now = datetime.datetime.now(datetime.timezone.utc)
    cutoff = (
        now - datetime.timedelta(seconds=max(1.0, window_s))
    ).isoformat()
    rows = await db.execute(
        "SELECT COALESCE(SUM("
        f"{db.json_num('total_tokens')}), 0) AS tok, "
        "MIN(created_at) AS first FROM model_usage "
        "WHERE tenant = ? AND created_at >= ?",
        (tenant, cutoff),
    )
    if not rows or not rows[0]["first"]:
        return None
    spent = int(rows[0]["tok"] or 0)
    try:
        first = datetime.datetime.fromisoformat(rows[0]["first"])
        age = max(0.0, (now - first).total_seconds())
    except ValueError:
        age = 0.0
    return spent, age


class TenancyRegistry:
    """In-memory QoS state + admission policy for the OpenAI surface."""

    def __init__(
        self,
        *,
        model_cap: int = 256,
        fair_watermark: float = 0.75,
        hard_ceiling: float = 2.0,
        default_rps: float = 0.0,
        default_burst: int = 0,
        default_concurrency: int = 0,
        default_token_budget: int = 0,
        budget_window_s: float = 3600.0,
        state_max: int = 65536,
        metrics_max_series: int = 50,
        clock=time.monotonic,
    ):
        self.model_cap = int(model_cap)
        self.fair_watermark = float(fair_watermark)
        self.hard_ceiling = max(1.0, float(hard_ceiling))
        self.default_rps = float(default_rps)
        self.default_burst = int(default_burst)
        self.default_concurrency = int(default_concurrency)
        self.default_token_budget = int(default_token_budget)
        self.budget_window_s = max(1.0, float(budget_window_s))
        self.state_max = max(16, int(state_max))
        self.metrics_max_series = max(1, int(metrics_max_series))
        self._clock = clock
        # tenant id -> state; OrderedDict = LRU order for the bound
        self._tenants: "collections.OrderedDict[str, _TenantState]" = (
            collections.OrderedDict()
        )
        # model name -> {tenant id -> in-flight} (live entries only)
        self._model_inflight: Dict[str, Dict[str, int]] = {}
        # durable-budget rehydration (PR 14 residual closed): an async
        # callable ``(tenant_id, window_s) -> (spent, age_s) | None``
        # consulted ONCE per tenant state before its first admission,
        # so a server restart re-seeds the rolling window from the
        # durable ``model_usage`` rows instead of reopening every
        # tenant's budget (see :func:`durable_budget_spend`)
        self.rehydrator = None
        self.rehydrated_tenants = 0
        # tenant id -> future resolved when its in-flight rehydration
        # read completes: concurrent first requests WAIT instead of
        # admitting against a still-unseeded budget
        self._rehydrating: Dict[str, object] = {}
        self.evictions = 0
        # /metrics export state: the first metrics_max_series tenants
        # get their own labeled series (sticky); everyone else rolls
        # into cumulative "_other" aggregates maintained INCREMENTALLY
        # so scrapes are O(named) and the rollup counters stay
        # monotonic through LRU eviction
        self._named_states: Dict[str, _TenantState] = {}
        self._tail_admitted = 0
        self._tail_shed: Dict[str, int] = {}
        self._tail_tokens = 0
        self._tail_inflight = 0

    @classmethod
    def from_config(cls, cfg) -> "TenancyRegistry":
        return cls(
            model_cap=int(getattr(cfg, "model_max_outstanding", 256)),
            fair_watermark=float(
                getattr(cfg, "tenant_fair_watermark", 0.75)
            ),
            hard_ceiling=float(
                getattr(cfg, "tenant_hard_ceiling", 2.0)
            ),
            default_rps=float(
                getattr(cfg, "tenant_default_rps", 0.0)
            ),
            default_burst=int(
                getattr(cfg, "tenant_default_burst", 0)
            ),
            default_concurrency=int(
                getattr(cfg, "tenant_default_concurrency", 0)
            ),
            default_token_budget=int(
                getattr(cfg, "tenant_default_token_budget", 0)
            ),
            budget_window_s=float(
                getattr(cfg, "tenant_budget_window_s", 3600.0)
            ),
            state_max=int(getattr(cfg, "tenant_state_max", 65536)),
            metrics_max_series=int(
                getattr(cfg, "tenant_metrics_max_series", 50)
            ),
        )

    # ---- tenant identity -------------------------------------------------

    @staticmethod
    def spec_for_principal(principal) -> TenantSpec:
        """Principal → service class. API keys carry their own QoS
        fields; session users / workers / system run under the
        defaults (enforced only when the registry's defaults say so)."""
        key = getattr(principal, "api_key", None)
        if key is not None:
            return TenantSpec(
                tenant=f"key:{key.id}",
                display=key.name or f"key:{key.id}",
                weight=max(1, int(getattr(key, "weight", 1))),
                priority=int(getattr(key, "priority", 0)),
                rate_rps=float(getattr(key, "rate_limit_rps", 0.0)),
                burst=int(getattr(key, "rate_limit_burst", 0)),
                max_concurrency=int(
                    getattr(key, "max_concurrency", 0)
                ),
                token_budget=int(getattr(key, "token_budget", 0)),
                budget_window_s=float(
                    getattr(key, "budget_window_s", 0.0)
                ),
            )
        kind = getattr(principal, "kind", "user")
        if kind == "user" and getattr(principal, "user", None):
            tid = f"user:{principal.user.id}"
            name = principal.user.username or tid
        elif kind == "worker":
            tid = f"worker:{getattr(principal, 'worker_id', 0)}"
            name = tid
        else:
            tid, name = "system", "system"
        return TenantSpec(tenant=tid, display=name)

    # ---- state -----------------------------------------------------------

    def _state(self, spec: TenantSpec, now: float) -> _TenantState:
        st = self._tenants.get(spec.tenant)
        if st is None:
            st = _TenantState(spec, now)
            if len(self._named_states) < self.metrics_max_series:
                st.named = True
                self._named_states[spec.tenant] = st
            self._tenants[spec.tenant] = st
            while len(self._tenants) > self.state_max:
                # evict the coldest IDLE tenant; in-flight ones carry
                # live accounting and must survive the bound. Lazy
                # scan (almost always the very first entry) — a
                # list() copy here would be an O(state_max) allocation
                # on the admit hot path every time the bound is hit
                doomed = next(
                    (
                        tid
                        for tid, state in self._tenants.items()
                        if state.inflight == 0
                    ),
                    None,
                )
                if doomed is None:
                    break
                if self._tenants[doomed].named:
                    # frees the named slot; the series simply
                    # disappears (an unnamed tenant's counts are
                    # already folded into the tail)
                    self._named_states.pop(doomed, None)
                del self._tenants[doomed]
                self.evictions += 1
        else:
            # key updated via /v2/api-keys: the spec travels with every
            # request, so quota/weight changes apply on the next call
            st.spec = spec
        st.last_seen = now
        self._tenants.move_to_end(spec.tenant)
        return st

    def _effective(self, spec: TenantSpec) -> Tuple[float, int, int, int]:
        """(rps, concurrency, token_budget, burst) with defaults."""
        rps = spec.rate_rps if spec.rate_rps > 0 else self.default_rps
        conc = (
            spec.max_concurrency
            if spec.max_concurrency > 0 else self.default_concurrency
        )
        budget = (
            spec.token_budget
            if spec.token_budget > 0 else self.default_token_budget
        )
        burst = spec.burst if spec.burst > 0 else self.default_burst
        return rps, conc, budget, burst

    # ---- durable-budget rehydration --------------------------------------

    async def ensure_rehydrated(
        self, spec: TenantSpec, now: Optional[float] = None
    ) -> None:
        """Seed a fresh tenant state's rolling budget from durable
        usage rows (once per state). Without this, a server restart
        zeroed every tenant's in-window spend — a client that had just
        exhausted its budget got a whole new window for free. Failures
        are logged and skipped (enforcement degrades open, billing
        truth stays in the rows)."""
        import asyncio

        now = self._clock() if now is None else now
        st = self._state(spec, now)
        if self.rehydrator is None or self._effective(spec)[2] <= 0:
            st.rehydrated = True
            return
        while not st.rehydrated:
            pending = self._rehydrating.get(spec.tenant)
            if pending is not None:
                # another request is mid-read for this tenant: WAIT
                # (marking rehydrated before the read completed would
                # let concurrent first requests admit against an
                # unseeded budget — the free window the seed closes);
                # loop afterwards: the owner may have been CANCELLED,
                # in which case this waiter becomes the owner
                await pending
                continue
            fut = asyncio.get_running_loop().create_future()
            self._rehydrating[spec.tenant] = fut
            try:
                result = await self._rehydrate_locked(spec, st, now)
            except BaseException:
                # cancellation (client disconnect mid-DB-read) must
                # NOT burn the once-only flag: the seed was never
                # applied, so the NEXT request retries it
                self._rehydrating.pop(spec.tenant, None)
                if not fut.done():
                    fut.set_result(None)
                raise
            # once per state on COMPLETION, success or failed read (a
            # broken rehydrator is not retried per request)
            st.rehydrated = True
            self._rehydrating.pop(spec.tenant, None)
            if not fut.done():
                fut.set_result(None)
            if result:
                self.rehydrated_tenants += 1

    async def _rehydrate_locked(
        self, spec: TenantSpec, st: "_TenantState", now: float
    ) -> bool:
        window = (
            spec.budget_window_s
            if spec.budget_window_s > 0 else self.budget_window_s
        )
        try:
            result = await self.rehydrator(spec.tenant, window)
        except Exception:
            import logging

            logging.getLogger(__name__).exception(
                "budget rehydration failed for %s", spec.tenant
            )
            return False
        if not result:
            return False
        spent, age = result
        if spent <= 0:
            return False
        if st.budget is None:
            st.budget = RollingBudget(window)
        # the window re-opens where the oldest surviving in-window row
        # says it did (capped just under one window so the seed cannot
        # immediately roll over; floored above zero — the monotonic
        # clock may be younger than the durable history)
        st.budget.window_start = max(
            1e-9, now - min(max(0.0, age), window * 0.999)
        )
        st.budget.spent = 0
        st.budget.record(int(spent), now)
        return True

    # ---- admission -------------------------------------------------------

    def admit(
        self,
        spec: TenantSpec,
        model: str,
        now: Optional[float] = None,
    ) -> Tuple[Decision, Optional[_Lease]]:
        """One admission decision; on success the caller must release
        the returned lease when the request fully completes (stream
        included) or the fair-share accounting leaks."""
        now = self._clock() if now is None else now
        st = self._state(spec, now)
        rps, conc, budget, burst = self._effective(spec)
        headers = self._headers(st, rps, burst, budget, now)

        if conc > 0 and st.inflight >= conc:
            return self._shed(
                st, REASON_CONCURRENCY, 1.0, headers
            ), None
        if rps > 0:
            cap = (
                float(burst) if burst > 0
                else TenantSpec(rate_rps=rps).bucket_capacity()
            )
            if st.bucket is None:
                st.bucket = TokenBucket(rps, cap, now)
            else:
                st.bucket.reconfigure(rps, cap)
            if not st.bucket.take(now):
                wait = st.bucket.seconds_until_token(now)
                headers["X-RateLimit-Remaining-Requests"] = "0"
                return self._shed(
                    st, REASON_RATE, wait, headers
                ), None
            headers["X-RateLimit-Remaining-Requests"] = str(
                st.bucket.remaining(now)
            )
        if budget > 0:
            window = (
                spec.budget_window_s
                if spec.budget_window_s > 0 else self.budget_window_s
            )
            if st.budget is None:
                st.budget = RollingBudget(window)
            else:
                st.budget.window = max(1.0, window)
            if st.budget.remaining(budget, now) <= 0:
                wait = st.budget.seconds_until_reset(now)
                headers["X-RateLimit-Remaining-Tokens"] = "0"
                return self._shed(
                    st, REASON_BUDGET, wait, headers
                ), None

        owns_cap = self.model_cap > 0 and self.fair_watermark > 0
        if owns_cap:
            verdict = self._fair_share(spec, model, now)
            if verdict is not None:
                return self._shed(
                    st, verdict, 1.0, headers
                ), None

        st.inflight += 1
        st.admitted_total += 1
        if not st.named:
            self._tail_admitted += 1
            self._tail_inflight += 1
        st.per_model[model] = st.per_model.get(model, 0) + 1
        self._model_inflight.setdefault(model, {})[spec.tenant] = (
            st.per_model[model]
        )
        return (
            Decision(
                admitted=True, tenant=spec.tenant, headers=headers,
                owns_model_cap=owns_cap,
            ),
            _Lease(self, spec.tenant, model),
        )

    def _fair_share(
        self, spec: TenantSpec, model: str, now: float
    ) -> Optional[str]:
        """Weighted-fair check for one saturated model, or None when
        admittable. Fair slots are weight-proportional among ACTIVE
        (in-flight) tenants of the same-or-higher priority — a
        higher-priority tenant's share ignores lower-priority demand
        entirely, which is what "shed lowest-priority first" means in
        slot form."""
        cap = self.model_cap
        per_tenant = self._model_inflight.get(model, {})
        total = sum(per_tenant.values())
        if total < self.fair_watermark * cap:
            return None
        if total >= self.hard_ceiling * cap:
            # physical backstop: past the ceiling nothing admits (the
            # floor-of-one fair slot would otherwise admit one request
            # per tenant — unbounded at millions of tenants)
            return REASON_SATURATED
        active_weight = 0
        for tid, n in per_tenant.items():
            if n <= 0 or tid == spec.tenant:
                continue
            other = self._tenants.get(tid)
            if other is None:
                continue
            if other.spec.priority >= spec.priority:
                active_weight += max(1, other.spec.weight)
        my_weight = max(1, spec.weight)
        fair = cap * my_weight / float(my_weight + active_weight)
        mine = per_tenant.get(spec.tenant, 0)
        if mine < max(1.0, fair):
            return None
        return REASON_FAIR

    def _shed(
        self,
        st: _TenantState,
        reason: str,
        retry_after: float,
        headers: Dict[str, str],
    ) -> Decision:
        st.shed_total += 1
        st.shed_by_reason[reason] = st.shed_by_reason.get(reason, 0) + 1
        if not st.named:
            self._tail_shed[reason] = (
                self._tail_shed.get(reason, 0) + 1
            )
        retry = max(1.0, retry_after)
        if retry == math.inf:
            retry = 60.0
        headers["Retry-After"] = str(int(math.ceil(retry)))
        return Decision(
            admitted=False, tenant=st.spec.tenant, reason=reason,
            retry_after=retry, headers=headers,
        )

    def _headers(
        self,
        st: _TenantState,
        rps: float,
        burst: int,
        budget: int,
        now: float,
    ) -> Dict[str, str]:
        """The applicable ``X-RateLimit-*`` set (OpenAI-style split
        into -Requests and -Tokens families)."""
        out: Dict[str, str] = {}
        if rps > 0:
            cap = (
                burst if burst > 0
                else int(TenantSpec(rate_rps=rps).bucket_capacity())
            )
            out["X-RateLimit-Limit-Requests"] = str(int(cap))
            if st.bucket is not None:
                out["X-RateLimit-Reset-Requests"] = (
                    f"{st.bucket.seconds_until_token(now):.3f}"
                )
        if budget > 0:
            out["X-RateLimit-Limit-Tokens"] = str(budget)
            if st.budget is not None:
                out["X-RateLimit-Remaining-Tokens"] = str(
                    st.budget.remaining(budget, now)
                )
                out["X-RateLimit-Reset-Tokens"] = str(
                    int(math.ceil(
                        st.budget.seconds_until_reset(now)
                    ))
                )
        return out

    def _end(self, tenant: str, model: str) -> None:
        st = self._tenants.get(tenant)
        if st is not None:
            if st.inflight > 0:
                st.inflight -= 1
                if not st.named and self._tail_inflight > 0:
                    self._tail_inflight -= 1
            n = st.per_model.get(model, 0) - 1
            if n <= 0:
                st.per_model.pop(model, None)
            else:
                st.per_model[model] = n
        slots = self._model_inflight.get(model)
        if slots is not None:
            n = slots.get(tenant, 0) - 1
            if n <= 0:
                slots.pop(tenant, None)
                if not slots:
                    self._model_inflight.pop(model, None)
            else:
                slots[tenant] = n

    # ---- usage feed (the PR 8 metering pipeline) -------------------------

    def record_tokens(
        self, tenant: str, tokens: int, now: Optional[float] = None
    ) -> None:
        """Charge ``tokens`` (prompt + completion) against the tenant's
        rolling budget — called by the proxy's usage recorder, so the
        budget rides the same counters ``/v2/usage/summary`` reports."""
        now = self._clock() if now is None else now
        st = self._tenants.get(tenant)
        if st is None:
            return
        st.tokens_total += max(0, int(tokens))
        if not st.named:
            self._tail_tokens += max(0, int(tokens))
        budget = self._effective(st.spec)[2]
        if budget <= 0:
            return
        if st.budget is None:
            window = (
                st.spec.budget_window_s
                if st.spec.budget_window_s > 0 else self.budget_window_s
            )
            st.budget = RollingBudget(window)
        st.budget.record(tokens, now)

    # ---- reads -----------------------------------------------------------

    def model_inflight(self, model: str) -> int:
        return sum(self._model_inflight.get(model, {}).values())

    def tenant_inflight(self, tenant: str) -> int:
        st = self._tenants.get(tenant)
        return st.inflight if st else 0

    def slo_samples(
        self, limit: int = 64
    ) -> List[Tuple[str, int, int]]:
        """(tenant, admitted_cum, shed_cum) for the most recently
        active tenants that have seen any shed or admission — the SLO
        evaluator turns each into a tenant-scoped shed-budget
        objective (bounded: label cardinality is an operator budget)."""
        items = [
            (tid, st.admitted_total, st.shed_total)
            for tid, st in self._tenants.items()
            if st.admitted_total or st.shed_total
        ]
        # OrderedDict iterates cold → hot; take the hot tail
        return items[-max(1, limit):]

    def snapshot(self, limit: int = 100) -> List[Dict]:
        """Operator view for ``GET /v2/debug/tenancy`` (hot tenants
        first, bounded)."""
        now = self._clock()
        out = []
        for tid, st in reversed(list(self._tenants.items())):
            if len(out) >= limit:
                break
            rps, conc, budget, burst = self._effective(st.spec)
            entry = {
                "tenant": tid,
                "display": st.spec.display,
                "weight": st.spec.weight,
                "priority": st.spec.priority,
                "inflight": st.inflight,
                "admitted_total": st.admitted_total,
                "shed_total": st.shed_total,
                "shed_by_reason": dict(st.shed_by_reason),
                "tokens_total": st.tokens_total,
                "limits": {
                    "rate_rps": rps,
                    "burst": burst,
                    "max_concurrency": conc,
                    "token_budget": budget,
                },
            }
            if st.budget is not None and budget > 0:
                entry["budget"] = {
                    "remaining": st.budget.remaining(budget, now),
                    "resets_in_s": round(
                        st.budget.seconds_until_reset(now), 3
                    ),
                }
            out.append(entry)
        return out

    def metrics_lines(self) -> List[str]:
        """Per-tenant admission/shed/token series, bounded: the first
        ``metrics_max_series`` concurrently tracked tenants hold their
        own label (sticky — never re-ranked, so series don't teleport
        between a name and the rollup); everyone else lands in
        cumulative ``tenant="_other"`` aggregates maintained
        incrementally at admit/shed/usage time. Scrapes are therefore
        O(named series), not O(all tenants), and every counter —
        ``_other`` included — stays monotonic through LRU eviction."""
        lines = ["# TYPE gpustack_tenant_requests_total counter"]

        def req_line(tenant: str, outcome: str, value: int) -> str:
            return (
                "gpustack_tenant_requests_total"
                f'{{tenant="{tenant}",outcome="{outcome}"}} {value}'
            )

        for tid, st in self._named_states.items():
            lines.append(req_line(tid, "admitted", st.admitted_total))
            for reason, n in sorted(st.shed_by_reason.items()):
                lines.append(req_line(tid, reason, n))
        if self._tail_admitted or self._tail_shed:
            lines.append(
                req_line("_other", "admitted", self._tail_admitted)
            )
            for reason, n in sorted(self._tail_shed.items()):
                lines.append(req_line("_other", reason, n))
        lines.append("# TYPE gpustack_tenant_inflight gauge")
        for tid, st in self._named_states.items():
            if st.inflight:
                lines.append(
                    f'gpustack_tenant_inflight{{tenant="{tid}"}} '
                    f"{st.inflight}"
                )
        if self._tail_inflight:
            lines.append(
                'gpustack_tenant_inflight{tenant="_other"} '
                f"{self._tail_inflight}"
            )
        lines.append("# TYPE gpustack_tenant_tokens_total counter")
        for tid, st in self._named_states.items():
            if st.tokens_total:
                lines.append(
                    f'gpustack_tenant_tokens_total{{tenant="{tid}"}} '
                    f"{st.tokens_total}"
                )
        if self._tail_tokens:
            lines.append(
                'gpustack_tenant_tokens_total{tenant="_other"} '
                f"{self._tail_tokens}"
            )
        return lines
