"""Reconcile-on-event controllers (reference gpustack/server/controllers.py).

Each controller consumes a watch stream (list+watch with RESYNC re-list —
see server/bus.py) and converges actual state toward spec:

- ModelController:    Model spec → N ModelInstances + a ModelRoute
  (reference controllers.py:300-359 sync_replicas + route notify)
- WorkerController:   lost workers → their instances UNREACHABLE
  (reference controllers.py:1347)
- WorkerSyncer:       heartbeat staleness → worker UNREACHABLE
  (reference server/worker_syncer.py:15)
"""

from __future__ import annotations

import asyncio
import datetime
import logging
from typing import Optional

from gpustack_tpu.schemas import (
    Model,
    ModelInstance,
    ModelInstanceState,
    ModelProvider,
    ModelProviderState,
    ModelRoute,
    ModelRouteTarget,
    Worker,
    WorkerState,
)
from gpustack_tpu.server.bus import Event, EventType
from gpustack_tpu.utils.profiling import timed

logger = logging.getLogger(__name__)


def role_deficit(model: Model, existing: list) -> list:
    """Role tags the spec still needs, given ``existing`` instances —
    prefill first (a disaggregated model with no prefill replica can
    serve but never hand KV off). Colocated models return untagged
    slots sized against ``replicas``. Callers cap the list themselves
    (e.g. rollout surge batches)."""
    by_role: dict = {}
    for inst in existing:
        by_role[inst.role] = by_role.get(inst.role, 0) + 1
    missing: list = []
    for role, want in model.role_spec().items():
        short = want - by_role.get(role, 0)
        if short > 0:
            missing.extend([role] * short)
    return missing


async def create_pending_instances(
    model: Model,
    count: int,
    generation: int,
    existing: list,
    prefix: Optional[str] = None,
    roles: Optional[list] = None,
) -> list:
    """Create ``count`` PENDING replicas for ``model`` tagged with
    ``generation``, skipping name collisions with ``existing``.

    Shared by replica sync (steady-state creation, ``model-N`` names)
    and the rollout controller's surge step (``model-gG-N`` names) so
    instance-creation defaults live in exactly one place. ``roles``
    assigns each new instance's disaggregated-serving role tag (the
    role deficit vs the spec — see :func:`role_deficit`); None derives
    it from ``existing``, so every creation path converges the role
    populations without thinking about them.
    """
    used = {i.name for i in existing}
    stem = prefix or model.name
    if roles is None:
        roles = role_deficit(model, existing)
    created = []
    idx = 0
    while len(created) < count:
        name = f"{stem}-{idx}"
        idx += 1
        if name in used:
            continue
        role = roles[len(created)] if len(created) < len(roles) else ""
        inst = await ModelInstance.create(ModelInstance(
            name=name,
            model_id=model.id,
            model_name=model.name,
            cluster_id=model.cluster_id,
            state=ModelInstanceState.PENDING,
            generation=generation,
            role=role,
        ))
        created.append(inst)
    return created


class Controller:
    """Base: consume a Record watch stream; re-list on RESYNC."""

    kind = ""
    record_cls = None

    def __init__(self) -> None:
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = asyncio.create_task(
            self.run(), name=type(self).__name__
        )

    def stop(self) -> None:
        if self._task:
            self._task.cancel()

    async def run(self) -> None:
        while True:
            agen = self.record_cls.subscribe(
                send_initial=True, heartbeat=30.0
            )
            try:
                async for event in agen:
                    if event.type == EventType.RESYNC:
                        break  # restart generator → fresh list
                    if event.type == EventType.HEARTBEAT:
                        continue
                    try:
                        await self.handle(event)
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        logger.exception(
                            "%s failed handling %s %s",
                            type(self).__name__, event.type, event.id,
                        )
            except asyncio.CancelledError:
                await agen.aclose()
                raise
            finally:
                await agen.aclose()

    async def handle(self, event: Event) -> None:
        raise NotImplementedError


class ModelController(Controller):
    record_cls = Model

    def __init__(self) -> None:
        super().__init__()
        from gpustack_tpu.utils.workqueue import WorkQueue

        # reconciles run through a coalescing work queue (reference
        # server/workqueue.py): a burst of updates to one model collapses
        # to a single reconcile, and a failed reconcile retries with
        # exponential backoff instead of being dropped
        self._queue = WorkQueue(
            self._reconcile, name="model-reconcile"
        )

    def start(self) -> None:
        super().start()
        self._queue.start()
        # Also watch INSTANCE deletions: an instance can disappear
        # outside any model update (user delete; subordinate-worker loss
        # tearing down a multi-host replica) and replica sync must
        # recreate it — model events alone never fire for those.
        self._inst_task = asyncio.create_task(
            self._watch_instance_deletes(), name="model-inst-watch"
        )

    def stop(self) -> None:
        super().stop()
        self._queue.stop()
        if getattr(self, "_inst_task", None):
            self._inst_task.cancel()

    async def _watch_instance_deletes(self) -> None:
        while True:
            try:
                agen = ModelInstance.subscribe(heartbeat=30.0)
                try:
                    async for event in agen:
                        if event.type == EventType.RESYNC:
                            break
                        if (
                            event.type == EventType.DELETED
                            and event.data
                            and event.data.get("model_id")
                        ):
                            self._queue.add(int(event.data["model_id"]))
                finally:
                    await agen.aclose()
            except asyncio.CancelledError:
                raise
            except Exception:
                # one transient subscribe/DB error must not silently
                # disable replica recreation for the rest of the
                # server's life
                logger.exception("instance-delete watch failed; retrying")
                await asyncio.sleep(2.0)

    async def handle(self, event: Event) -> None:
        if event.type == EventType.DELETED:
            for inst in await ModelInstance.filter(model_id=event.id):
                await inst.delete()
            # drop every route this model backed: its own name AND any
            # LoRA alias routes (reference deletes lora child routes with
            # the base model)
            for route in await ModelRoute.all():
                if any(t.model_id == event.id for t in route.targets):
                    remaining = [
                        t for t in route.targets
                        if t.model_id != event.id
                    ]
                    if remaining:
                        await route.update(targets=remaining)
                    else:
                        await route.delete()
            return
        self._queue.add(event.id)

    async def _reconcile(self, model_id: int) -> None:
        model = await Model.get(model_id)
        if model is None:
            return
        await self._sync_replicas(model)
        await self._ensure_route(model)

    @timed(threshold_s=5.0, name="controllers.replica_sync")
    async def _sync_replicas(self, model: Model) -> None:
        from gpustack_tpu.schemas import Rollout

        if await Rollout.active_for(model.id) is not None:
            # a mid-flight rollout owns the replica set: it deliberately
            # runs spec+surge instances and drains batches itself —
            # count enforcement here would fight its arithmetic
            return
        instances = await ModelInstance.filter(model_id=model.id)
        missing = role_deficit(model, instances)
        if missing:
            # new replicas tagged with the spec version they will
            # serve — the RolloutController converges tags — and with
            # their disaggregated-serving role (the deficit per role,
            # so prefill and decode populations converge independently)
            created = await create_pending_instances(
                model, len(missing),
                model.generation, instances, roles=missing,
            )
            for inst in created:
                instances.append(inst)
                logger.info(
                    "created instance %s%s", inst.name,
                    f" (role {inst.role})" if inst.role else "",
                )
        # excess is judged PER ROLE: a disaggregated model with a
        # decode surplus must never drain a prefill replica for it
        # (and flipping disaggregation on/off converges the now-
        # unwanted role's population out)
        by_role: dict = {}
        for inst in instances:
            by_role.setdefault(inst.role, []).append(inst)
        spec_roles = model.role_spec()
        for role, insts in by_role.items():
            excess = len(insts) - spec_roles.get(role, 0)
            if excess > 0:
                await self._retire_excess(insts, excess)

    async def _retire_excess(self, insts: list, excess: int) -> None:
        # retire non-running first, then newest
        order = {
            ModelInstanceState.RUNNING: 1,
        }
        doomed = sorted(
            insts,
            key=lambda i: (order.get(i.state, 0), -i.id),
        )[:excess]
        for inst in doomed:
            if inst.state == ModelInstanceState.DRAINING:
                continue  # already on its way out
            if inst.state == ModelInstanceState.RUNNING:
                # graceful scale-down: DRAINING holds the chip claim
                # while the worker finishes in-flight requests, then
                # the worker retires the row itself — a hard delete
                # would free the claim under a still-serving engine
                logger.info(
                    "draining instance %s for scale-down", inst.name
                )
                await inst.update(
                    state=ModelInstanceState.DRAINING,
                    state_message="scale-down drain",
                )
                continue
            logger.info("retiring instance %s", inst.name)
            await inst.delete()

    async def _ensure_route(self, model: Model) -> None:
        route = await ModelRoute.first(name=model.name)
        target = ModelRouteTarget(
            model_id=model.id, model_name=model.name, weight=100
        )
        if route is None:
            await ModelRoute.create(
                ModelRoute(name=model.name, targets=[target])
            )
        elif not any(t.model_id == model.id for t in route.targets):
            await route.update(targets=route.targets + [target])
        await self._ensure_lora_routes(model)

    async def _ensure_lora_routes(self, model: Model) -> None:
        """One route alias per LoRA adapter: clients can request the
        adapter by name, OpenAI-style (reference
        server/lora_model_routes.py create_lora_model_routes — one
        ModelRoute+Target per lora_list entry, idempotent, cross-model
        name conflicts rejected). Divergence, documented: this engine
        merges adapters at load (engine/weights.py), so every alias of a
        deployment serves the same merged weights — the alias surface
        exists for API compatibility, not per-request adapter switching."""
        import os as _os

        def alias_for(adapter: str) -> str:
            return _os.path.basename(str(adapter).rstrip("/")) or adapter

        wanted = {
            f"{model.name}:{alias_for(a)}" for a in model.lora_adapters
        }
        # reconcile removals: an adapter dropped from the model must take
        # its alias route with it (creation alone would leak stale
        # aliases until model deletion)
        prefix = f"{model.name}:"
        for route in await ModelRoute.all():
            if (
                route.name.startswith(prefix)
                and route.name not in wanted
                and all(t.model_id == model.id for t in route.targets)
            ):
                logger.info("removing stale LoRA route %r", route.name)
                await route.delete()
        for adapter in model.lora_adapters:
            route_name = f"{model.name}:{alias_for(adapter)}"
            existing = await ModelRoute.first(name=route_name)
            if existing is not None:
                if any(
                    t.model_id == model.id for t in existing.targets
                ):
                    continue     # already ours — idempotent
                logger.error(
                    "LoRA route name %r conflicts with an existing route "
                    "not owned by model %s; skipping alias",
                    route_name, model.name,
                )
                continue
            await ModelRoute.create(ModelRoute(
                name=route_name,
                targets=[ModelRouteTarget(
                    model_id=model.id, model_name=model.name, weight=100
                )],
            ))
            logger.info(
                "created LoRA route %r -> model %s", route_name,
                model.name,
            )


class ModelProviderController(Controller):
    """Probe external providers and keep their state/model list fresh.

    Reference: ModelProviderController (controllers.py:2779) reprograms
    the Higress ai-proxy on provider changes; with an in-process gateway
    there is nothing to reprogram, so the controller's remaining job is
    liveness: GET {base_url}/models with the provider's credential on
    CREATE/UPDATE, record reachability + the advertised model ids.
    """

    record_cls = ModelProvider

    probe_timeout = 15.0

    async def handle(self, event: Event) -> None:
        if event.type == EventType.DELETED:
            return
        if event.type == EventType.UPDATED and event.changes and not (
            {"base_url", "api_key", "extra_headers", "enabled"}
            & set(event.changes)
        ):
            return  # state-only writes (incl. our own) don't re-probe
        provider = await ModelProvider.get(event.id)
        if provider is None or not provider.enabled:
            return
        await self.probe(provider)

    async def probe(self, provider) -> None:
        import aiohttp

        headers = dict(provider.extra_headers)
        if provider.api_key:
            headers["Authorization"] = f"Bearer {provider.api_key}"
        url = f"{provider.base_url.rstrip('/')}/models"
        try:
            async with aiohttp.ClientSession() as session:
                async with session.get(
                    url,
                    headers=headers,
                    timeout=aiohttp.ClientTimeout(total=self.probe_timeout),
                ) as resp:
                    body = await resp.json(content_type=None)
                    ok = resp.status == 200
                    status = resp.status
        except Exception as e:
            await provider.update(
                state=ModelProviderState.UNREACHABLE,
                state_message=str(e)[:200],
            )
            return
        if not ok:
            await provider.update(
                state=ModelProviderState.UNREACHABLE,
                state_message=f"/models returned HTTP {status}",
            )
            return
        names = []
        if isinstance(body, dict):
            names = [
                str(m.get("id"))
                for m in body.get("data") or []
                if isinstance(m, dict) and m.get("id")
            ]
        await provider.update(
            state=ModelProviderState.ACTIVE,
            state_message="",
            discovered_models=sorted(names),
        )


class RouteTargetController(Controller):
    """Sync ModelRouteTarget health from instance/provider state
    (reference ModelRouteTargetController._sync_state,
    server/controllers.py:2946-3030: a target is ACTIVE when its model
    has ready replicas or its provider is enabled; resolution then skips
    unavailable targets without probing them)."""

    record_cls = ModelInstance

    def start(self) -> None:
        super().start()
        self._provider_task = asyncio.create_task(
            self._watch_providers(), name="route-target-providers"
        )

    def stop(self) -> None:
        super().stop()
        if getattr(self, "_provider_task", None):
            self._provider_task.cancel()

    async def handle(self, event: Event) -> None:
        data = event.data or {}
        model_id = int(data.get("model_id") or 0)
        if not model_id:
            return
        if event.type == EventType.UPDATED and not (
            event.changes and "state" in event.changes
        ):
            return
        await self.sync_model_targets(model_id)

    async def sync_model_targets(self, model_id: int) -> None:
        running = await ModelInstance.filter(
            model_id=model_id, state=ModelInstanceState.RUNNING
        )
        state = "active" if running else "unavailable"
        for route_id in [r.id for r in await ModelRoute.all()]:
            # re-fetch right before writing: Record.save overwrites the
            # whole document, so a list snapshot taken before the awaits
            # could clobber a target another controller just appended
            route = await ModelRoute.get(route_id)
            if route is None:
                continue
            # copies, not in-place mutation: Record.update diffs old vs
            # new and a mutated shared list compares equal to itself
            changed = False
            new_targets = []
            for t in route.targets:
                if t.provider_id == 0 and t.model_id == model_id and (
                    t.state != state
                ):
                    t = t.model_copy(update={"state": state})
                    changed = True
                new_targets.append(t)
            if changed:
                await route.update(targets=new_targets)

    async def _watch_providers(self) -> None:
        while True:
            try:
                agen = ModelProvider.subscribe(heartbeat=30.0)
                try:
                    async for event in agen:
                        if event.type == EventType.RESYNC:
                            break
                        if event.type == EventType.HEARTBEAT:
                            continue
                        await self._sync_provider_targets(event)
                finally:
                    await agen.aclose()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("provider target sync failed; retrying")
                await asyncio.sleep(2.0)

    async def _sync_provider_targets(self, event: Event) -> None:
        pid = event.id
        if event.type == EventType.DELETED:
            state = "unavailable"
        else:
            provider = await ModelProvider.get(pid)
            if provider is None:
                return
            state = (
                "active"
                if provider.enabled
                and provider.state != ModelProviderState.UNREACHABLE
                else "unavailable"
            )
        for route_id in [r.id for r in await ModelRoute.all()]:
            route = await ModelRoute.get(route_id)
            if route is None:
                continue
            changed = False
            new_targets = []
            for t in route.targets:
                if t.provider_id == pid and t.state != state:
                    t = t.model_copy(update={"state": state})
                    changed = True
                new_targets.append(t)
            if changed:
                await route.update(targets=new_targets)


# States a lost worker parks in UNREACHABLE: every CLAIM-HOLDING state
# only its agent could have progressed. SCHEDULED/DOWNLOADING/STARTING
# used to be left in place (chaos finding: stuck forever —
# stuck-reschedule covers only ANALYZING/SCHEDULED via the scheduler,
# and not placed-and-claimed rows). ERROR is deliberately absent:
# it holds no chip claim, so parking it in UNREACHABLE (a claiming
# state) would resurrect chips the allocator may already have re-issued
# — ERROR rows on dead workers are rescued by deletion instead
# (InstanceRescuer).
_PARK_UNREACHABLE_STATES = (
    ModelInstanceState.SCHEDULED,
    ModelInstanceState.DOWNLOADING,
    ModelInstanceState.STARTING,
    ModelInstanceState.RUNNING,
)


def _is_subordinate(inst: ModelInstance, worker_id: int) -> bool:
    return any(
        sub.worker_id == worker_id for sub in inst.subordinate_workers
    )


async def _teardown_for_reschedule(
    inst: ModelInstance, worker_id: int, reason: str
) -> None:
    """Multi-host replica lost a member host: it cannot function and
    cannot recover in place — delete it. The DELETED event stops the
    surviving hosts' engines (freeing their chips) and the
    ModelController's replica sync creates a fresh instance to
    reschedule."""
    logger.warning(
        "instance %s %s (worker %d); tearing down for reschedule",
        inst.name, reason, worker_id,
    )
    await inst.delete()


async def _leader_worker_lost(
    inst: ModelInstance, worker_id: int
) -> None:
    """One leader-owned instance on a lost worker. Shared by the
    edge-triggered path (WorkerController, on the worker-state event)
    and the level-triggered sweep (InstanceRescuer, every scan) — the
    sweep exists because a server crash between the worker flip and
    these per-instance writes would otherwise lose the edge forever."""
    if inst.state == ModelInstanceState.DRAINING:
        # same semantics as RUNNING below: the worker may be
        # partitioned, not dead, with its engine still serving its
        # last streams — deleting the row here would free the chip
        # claim under a live engine and invite a double placement.
        # UNREACHABLE holds the claim; the rescue grace window (or
        # the worker's return) takes it from there.
        await inst.update(
            state=ModelInstanceState.UNREACHABLE,
            state_message="worker unreachable during drain",
        )
        return
    if inst.state not in _PARK_UNREACHABLE_STATES:
        return
    if inst.subordinate_workers:
        # multi-host replica that lost its LEADER: followers cannot
        # function alone
        await _teardown_for_reschedule(inst, worker_id, "lost its leader")
    else:
        await inst.update(
            state=ModelInstanceState.UNREACHABLE,
            state_message=f"worker unreachable (was {inst.state.value})",
        )


class WorkerController(Controller):
    record_cls = Worker

    async def handle(self, event: Event) -> None:
        if event.type == EventType.DELETED:
            # single pass: leader-owned rows AND multi-host replicas
            # that used this worker as a subordinate (those cannot
            # function with a member host gone)
            for inst in await ModelInstance.all():
                if inst.worker_id == event.id:
                    await inst.delete()
                elif _is_subordinate(inst, event.id):
                    await _teardown_for_reschedule(
                        inst, event.id, "lost subordinate (worker deleted)"
                    )
            return
        if event.type != EventType.UPDATED or not event.changes:
            return
        state_change = event.changes.get("state")
        if not state_change:
            return
        _, new = state_change
        if new == WorkerState.UNREACHABLE.value:
            # ONE pass over the table (was: indexed filter for
            # leader-owned rows + a second full scan for subordinates —
            # two queries and two walks per worker state change)
            for inst in await ModelInstance.all():
                if inst.worker_id == event.id:
                    await _leader_worker_lost(inst, event.id)
                elif _is_subordinate(inst, event.id):
                    # A multi-host replica with this worker as a
                    # SUBORDINATE cannot function (its collectives span
                    # the dead host) and cannot recover in place
                    # (reference role: Ray-cluster member loss fails
                    # the whole vLLM multinode replica).
                    await _teardown_for_reschedule(
                        inst, event.id, "lost subordinate"
                    )
        elif new == WorkerState.READY.value:
            # instances recover via the worker's own state sync: the
            # heartbeat that flipped the worker READY also tells the
            # agent it recovered, and the agent reconciles (worker.py
            # post-recovery reconcile) — nothing to do server-side.
            pass



class WorkerSyncer:
    """Flip workers to UNREACHABLE when heartbeats go stale.

    ``freshness_source`` (worker_id -> newest heartbeat iso, or "") is
    the write combiner's in-memory liveness map: a heartbeat this
    server RECEIVED but has not yet flushed (coalescing debounce, or
    the overload-degradation ladder deferring writes) must never read
    as staleness — that is exactly the "DB slow ⇒ healthy instances
    parked" failure mode the combiner exists to prevent."""

    def __init__(
        self,
        stale_after: float = 45.0,
        interval: float = 15.0,
        freshness_source=None,
    ):
        self.stale_after = stale_after
        self.interval = interval
        self.freshness_source = freshness_source
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = asyncio.create_task(self.run(), name="WorkerSyncer")

    def stop(self) -> None:
        if self._task:
            self._task.cancel()

    async def run(self) -> None:
        while True:
            try:
                await self.sync_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("worker sync failed")
            await asyncio.sleep(self.interval)

    @timed(threshold_s=5.0, name="controllers.worker_sync_scan")
    async def sync_once(self) -> None:
        now = datetime.datetime.now(datetime.timezone.utc)
        for worker in await Worker.filter(state=WorkerState.READY):
            heartbeat_at = worker.heartbeat_at
            if self.freshness_source is not None:
                # in-memory liveness beats the (possibly deferred) DB
                # column; ISO-8601 strings order lexicographically
                fresh = self.freshness_source(worker.id) or ""
                if fresh > heartbeat_at:
                    heartbeat_at = fresh
            if not heartbeat_at:
                continue
            try:
                last = datetime.datetime.fromisoformat(heartbeat_at)
            except ValueError:
                continue
            age = (now - last).total_seconds()
            if age > self.stale_after:
                logger.warning(
                    "worker %s heartbeat stale (%.0fs); marking unreachable",
                    worker.name, age,
                )
                await worker.update(
                    state=WorkerState.UNREACHABLE,
                    state_message=f"no heartbeat for {age:.0f}s",
                )


class InstanceRescuer:
    """Tear down UNREACHABLE instances whose worker never came back.

    Closes the known self-healing hole: a permanently dead worker left
    its instances parked in UNREACHABLE forever (nothing rescued them),
    so a model silently stayed under-replicated until an operator
    intervened. Semantics:

    - WITHIN the grace window (``unreachable_rescue_after``) the row —
      and its chip claim — is held untouched: the worker may be
      partitioned, not dead, with a live engine; deleting early would
      invite a double placement onto claimed chips.
    - PAST the window, single-host UNREACHABLE instances are deleted;
      the ModelController's replica sync recreates them and the
      scheduler places the replacement on a healthy worker. Multi-host
      replicas never reach this loop — worker loss tears them down
      immediately (WorkerController).
    - A worker that returned (READY) is never rescued out from under:
      its agent's post-recovery reconcile re-drives the instance, and a
      delete here would race that into a double placement.
    """

    def __init__(self, grace: float = 300.0, interval: float = 15.0):
        self.grace = grace
        self.interval = interval
        self.rescued_total = 0
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        # ALWAYS runs: grace <= 0 disables only the teardown sweeps;
        # the level-triggered park sweep is a correctness mechanism
        # (crash-lost worker edges) independent of the rescue deletion
        if self.grace <= 0:
            logger.info(
                "instance rescue teardown disabled (grace <= 0); "
                "park sweep stays on"
            )
        self._task = asyncio.create_task(
            self.run(), name="InstanceRescuer"
        )

    def stop(self) -> None:
        if self._task:
            self._task.cancel()

    async def run(self) -> None:
        while True:
            try:
                await self.sync_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("instance rescue scan failed")
            await asyncio.sleep(self.interval)

    @timed(threshold_s=5.0, name="controllers.rescuer_scan")
    async def sync_once(self) -> None:
        now = datetime.datetime.now(datetime.timezone.utc)
        # one worker prefetch per scan, shared by every sweep (this
        # loop runs every heartbeat interval — per-instance Worker.get
        # would be an N+1 on a hot path)
        workers = {w.id: w for w in await Worker.all()}
        await self._park_sweep(workers)
        if self.grace <= 0:
            return  # teardown disabled; parking convergence only
        for inst in await ModelInstance.filter(
            state=ModelInstanceState.UNREACHABLE
        ):
            # updated_at is the moment the row was parked UNREACHABLE
            # (nothing else may legally write a parked row)
            age = self._age(inst.updated_at, now)
            if age is None or age <= self.grace:
                continue
            worker = workers.get(inst.worker_id or 0)
            if worker is not None and worker.state == WorkerState.READY:
                # worker is back; its agent re-drives the instance
                continue
            await self._rescue(
                inst, ModelInstanceState.UNREACHABLE,
                f"worker {worker.name if worker else inst.worker_id} "
                f"unreachable for {age:.0f}s (> {self.grace:.0f}s grace)",
            )
        # ERROR rows hold NO chip claim, so they are never parked in
        # UNREACHABLE (that would resurrect a claim the allocator may
        # have re-issued) — but on a dead worker nothing will ever
        # restart them either. Delete after the WORKER has been gone
        # past grace so replica sync re-places them.
        for inst in await ModelInstance.filter(
            state=ModelInstanceState.ERROR
        ):
            if not inst.worker_id:
                continue
            worker = workers.get(inst.worker_id)
            if worker is not None and worker.state == WorkerState.READY:
                continue  # restart_on_error is the live-worker path
            # grace measured from when the WORKER was marked lost (its
            # row stops changing once heartbeats stop), not from the
            # instance's own — possibly ancient — error time
            age = self._age(
                worker.updated_at if worker else inst.updated_at, now
            )
            if age is None or age <= self.grace:
                continue
            await self._rescue(
                inst, ModelInstanceState.ERROR,
                f"errored on worker {inst.worker_id}, gone for "
                f"{age:.0f}s (> {self.grace:.0f}s grace)",
            )

    async def _park_sweep(self, workers) -> None:
        """LEVEL-triggered parking: re-derive "this instance's worker is
        lost" from current state, not just from worker-state edge
        events. A server crash between WorkerSyncer's UNREACHABLE flip
        and WorkerController's per-instance park writes loses the edge
        forever — on reboot the controller replays rows as synthetic
        CREATED events it ignores, and the dead worker never produces
        another edge. This sweep converges those instances on the next
        scan. Writes are idempotent with the edge path (same states),
        so the two racing is harmless."""

        def lost(worker_id) -> bool:
            if not worker_id:
                return False
            w = workers.get(worker_id)
            return w is None or w.state == WorkerState.UNREACHABLE

        for inst in await ModelInstance.all():
            if inst.worker_id and lost(inst.worker_id):
                await _leader_worker_lost(inst, inst.worker_id)
            elif inst.subordinate_workers:
                gone = [
                    sub.worker_id
                    for sub in inst.subordinate_workers
                    if lost(sub.worker_id)
                ]
                if gone:
                    await _teardown_for_reschedule(
                        inst, gone[0], "lost subordinate (sweep)"
                    )

    @staticmethod
    def _age(
        iso: str, now: datetime.datetime
    ) -> Optional[float]:
        try:
            return (now - datetime.datetime.fromisoformat(iso)).total_seconds()
        except ValueError:
            return None

    async def _rescue(
        self,
        inst: ModelInstance,
        expected_state: ModelInstanceState,
        why: str,
    ) -> None:
        # re-fetch BOTH rows right before acting: the agent may have
        # recovered and re-driven the instance while this scan awaited
        # — and the worker snapshot from the top of the scan can be
        # stale in exactly that window. Deleting a freshly re-driven
        # instance (or one whose worker just came back) would throw
        # away a live engine and double-place its replica.
        fresh = await ModelInstance.get(inst.id)
        if fresh is None or fresh.state != expected_state:
            return
        worker = await Worker.get(fresh.worker_id or 0)
        if worker is not None and worker.state == WorkerState.READY:
            return
        logger.warning(
            "rescuing instance %s: %s; tearing down for re-placement",
            inst.name, why,
        )
        self.rescued_total += 1
        await fresh.delete()
