"""Server-side control plane: event bus, services, controllers, scheduler
wiring, HTTP app — the reference's ``gpustack/server`` layer re-designed
around an asyncio core (SURVEY.md §2.3)."""
