"""Slice-aware scheduler (reference gpustack/scheduler re-designed for TPU).

The schedulable unit is chips on an ICI slice; a placement is a mesh plan
(SURVEY.md §2.10-2.11), not a GPU index set + engine flags.
"""

from gpustack_tpu.scheduler.calculator import evaluate_model
from gpustack_tpu.scheduler.scheduler import Scheduler

__all__ = ["Scheduler", "evaluate_model"]
