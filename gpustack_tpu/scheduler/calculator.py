"""HBM resource estimation: model spec → chips + mesh plan + bytes.

Replaces the reference's gguf-parser pipeline (reference
gpustack/scheduler/calculator.py shells out to a Go binary for layer-wise
VRAM estimates): on TPU the claim is weights + KV cache + activation
headroom against HBM per chip, and the output is a mesh plan whose product
is chips-per-replica.

Weight/KV math comes from ModelConfig (exact parameter counts, attention-
type-aware KV sizing — the reference's selector parses the same
hyperparameters, base_candidate_selector.py:56-165). When a local
checkpoint directory is present, the native ``model-meta`` tool (C++,
native/) supplies exact safetensors tensor sizes instead.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os
from typing import Optional

from gpustack_tpu.models.config import (
    ModelConfig,
    PRESETS,
    config_from_hf,
)
from gpustack_tpu.parallel.mesh import MeshPlan, plan_mesh
from gpustack_tpu.schemas import ComputedResourceClaim, Model

logger = logging.getLogger(__name__)

# Fraction of per-chip HBM the engine may plan against (the rest covers
# activations, XLA scratch, and fragmentation) — analogue of vLLM's
# gpu-memory-utilization handled by the reference selector.
HBM_UTILIZATION = 0.9


class EvaluationError(Exception):
    """Model cannot be evaluated (bad source, unknown architecture...)."""


@dataclasses.dataclass
class ModelEvaluation:
    config: ModelConfig
    weight_bytes: int
    kv_cache_bytes: int
    overhead_bytes: int

    @property
    def total_bytes(self) -> int:
        return self.weight_bytes + self.kv_cache_bytes + self.overhead_bytes


def resolve_raw_config(model: Model) -> Optional[dict]:
    """Raw HF-style ``config.json`` dict for the model's source, or None
    when the source has no such file (presets; diffusers layouts, whose
    ``model_index.json`` is handled by ``resolve_model_config``).

    Network sources are disk-cached (hf_hub cache / the ModelScope
    config cache), so callers may use this freely on every reconcile.
    """
    if model.preset:
        return None
    if model.local_path:
        if os.path.exists(
            os.path.join(model.local_path, "model_index.json")
        ):
            return None
        import json as _json

        try:
            with open(
                os.path.join(model.local_path, "config.json")
            ) as f:
                return _json.load(f)
        except (OSError, ValueError) as e:
            raise EvaluationError(
                f"cannot read config from {model.local_path}: {e}"
            )
    if model.huggingface_repo_id:
        # Fetch just config.json (tiny; hf_hub caches it, so offline
        # re-evaluation works once cached) — the reference does the same
        # HF-config probing server-side (scheduler/evaluator.py HF rate
        # limiter).
        import json as _json

        try:
            from huggingface_hub import hf_hub_download

            path = hf_hub_download(
                model.huggingface_repo_id, "config.json"
            )
            with open(path) as f:
                return _json.load(f)
        except Exception as e:
            raise EvaluationError(
                f"cannot fetch config for "
                f"{model.huggingface_repo_id!r}: {e}"
            )
    if model.model_scope_model_id:
        return _modelscope_config_cached(model.model_scope_model_id)
    raise EvaluationError(
        "model has no source (preset/local_path/hf/modelscope)"
    )


def resolve_model_config(model: Model, raw: Optional[dict] = None):
    """Model spec → engine config. ``raw`` lets callers that already
    fetched the raw config dict (model_registry.detect_categories) skip
    a second source resolution."""
    from gpustack_tpu.models.diffusion import (
        DIFFUSION_PRESETS,
        config_from_diffusers,
    )
    from gpustack_tpu.models.whisper import (
        WHISPER_PRESETS,
        config_from_hf_whisper,
    )

    from gpustack_tpu.models.tts import TTS_PRESETS
    from gpustack_tpu.models.vlm import VLM_PRESETS, get_vlm_config

    if model.preset:
        if model.preset in WHISPER_PRESETS:
            return WHISPER_PRESETS[model.preset]
        if model.preset in TTS_PRESETS:
            return TTS_PRESETS[model.preset]
        if model.preset in VLM_PRESETS:
            # placement math runs on the language half (the tower is a
            # rounding error next to the LLM weights + KV cache)
            return get_vlm_config(model.preset).language
        if model.preset in DIFFUSION_PRESETS:
            return DIFFUSION_PRESETS[model.preset]
        if model.preset not in PRESETS:
            raise EvaluationError(f"unknown preset {model.preset!r}")
        return PRESETS[model.preset]
    if raw is None:
        raw = resolve_raw_config(model)
    if raw is None:
        from gpustack_tpu.engine.gguf import config_from_gguf, gguf_file_in

        gguf_path = gguf_file_in(model.local_path or "")
        if gguf_path:
            try:
                return config_from_gguf(gguf_path, name=model.name)
            except ValueError as e:
                raise EvaluationError(str(e))
        # diffusers-format layout = image pipeline
        return config_from_diffusers(model.local_path, name=model.name)
    name = (
        model.huggingface_repo_id
        or model.model_scope_model_id
        or model.name
        or os.path.basename(str(model.local_path).rstrip("/"))
    )
    try:
        if raw.get("model_type") == "whisper":
            return config_from_hf_whisper(raw, name=model.name or name)
        if raw.get("model_type") in ("tts", "fastspeech"):
            # in-repo TTS checkpoint format: config.json names a preset
            # (same contract as build_audio_engine_from_args)
            preset = raw.get("preset", "tts-base")
            if preset not in TTS_PRESETS:
                raise EvaluationError(f"unknown TTS preset {preset!r}")
            return TTS_PRESETS[preset]
        return config_from_hf(raw, name=name)
    except (KeyError, ValueError) as e:
        raise EvaluationError(
            f"unsupported model config for {name!r}: {e}"
        )


def _modelscope_config_cached(model_id: str) -> dict:
    """config.json for a ModelScope model, disk-cached like the HF
    branch (hf_hub_download caches): repeat evaluations don't re-hit the
    network, and offline re-evaluation keeps working once cached."""
    import json as _json
    import re as _re

    safe = _re.sub(r"[^A-Za-z0-9_.-]", "--", model_id)
    cache_dir = os.path.join(
        os.path.expanduser("~"), ".cache", "gpustack_tpu", "ms-configs"
    )
    cache = os.path.join(cache_dir, safe + ".json")
    if os.path.exists(cache):
        try:
            with open(cache) as f:
                return _json.load(f)
        except (OSError, ValueError):
            pass
    from gpustack_tpu.worker.downloaders import modelscope_fetch_config

    try:
        raw = modelscope_fetch_config(model_id)
    except Exception as e:
        raise EvaluationError(
            f"cannot fetch config for {model_id!r}: {e}"
        )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        with open(cache + ".tmp", "w") as f:
            _json.dump(raw, f)
        os.replace(cache + ".tmp", cache)
    except OSError:
        pass
    return raw


from gpustack_tpu.utils.profiling import timed

# KV slots a PREFILL-role replica plans for: it computes prompt KV and
# hands it off rather than decoding a full continuous batch, so a
# couple of in-flight prefills bound its resident KV. This is what
# makes context length a real placement dimension per role — a 32k-
# context model's decode replicas claim the full ``max_slots`` KV
# while its prefill replicas fit on fewer chips.
PREFILL_ROLE_KV_SLOTS = 2


@timed(threshold_s=5.0, name="scheduler.evaluate_model")
def evaluate_model(model: Model, role: str = "") -> ModelEvaluation:
    """HBM claim for one replica. ``role`` (disaggregated serving) is
    a KV-sizing dimension: prefill-role replicas hold at most
    ``PREFILL_ROLE_KV_SLOTS`` sequences of KV; decode/colocated
    replicas hold ``max_slots``."""
    cfg = resolve_model_config(model)
    weight_bits = 8 if model.quantization == "int8" else 16
    weight_bytes = cfg.weight_bytes(weight_bits)
    if model.local_path:
        # exact accounting from the native model-meta tool (checkpoint
        # tensors on disk beat config-derived estimates)
        from gpustack_tpu.utils.native import run_model_meta

        meta = run_model_meta(model.local_path)
        if meta and meta.get("total_bytes"):
            disk_bytes = int(meta["total_bytes"])
            if model.quantization == "int8":
                # engine int8 quantization only shrinks 16/32-bit float
                # tensors; already-quantized checkpoint bytes (GGUF Q*,
                # int8 safetensors) load as-is
                by_dtype = meta.get("bytes_by_dtype") or {}
                wide = sum(
                    v for k, v in by_dtype.items()
                    if k in ("F16", "BF16", "F32", "F64")
                )
                narrow = disk_bytes - wide
                disk_bytes = narrow + wide // 2 + wide // 256
            weight_bytes = disk_bytes
    # KV buffers follow the model's compute dtype: KVCache.create
    # allocates bf16 only for dtype == "bfloat16" and fp32 for anything
    # else, so mirror that exact rule or fp32 deployments undercount 2x
    kv_bits = 16 if getattr(cfg, "dtype", "bfloat16") == "bfloat16" else 32
    kv_slots = model.max_slots
    if role == "prefill":
        kv_slots = min(model.max_slots, PREFILL_ROLE_KV_SLOTS)
    kv_bytes = (
        cfg.kv_cache_bytes_per_token(kv_bits)
        * model.max_seq_len
        * kv_slots
    )
    # activation + runtime overhead: prefill attention scratch dominates;
    # scale with seq len, floor at 256 MiB (audio configs use d_model)
    hidden = getattr(cfg, "hidden_size", 0) or cfg.d_model
    overhead = max(
        256 * 2**20,
        int(2 * model.max_seq_len * hidden * 4 * 8),
    )
    return ModelEvaluation(
        config=cfg,
        weight_bytes=weight_bytes,
        kv_cache_bytes=kv_bytes,
        overhead_bytes=overhead,
    )


def fleet_chip_budget(workers, distributable: bool):
    """(max_chips, allowed_counts) for a filtered fleet.

    ``allowed_counts`` = per-worker ICI-tileable sub-slice sizes
    (policies/topology) plus, for distributable models, power-of-two
    whole-host multiples across a slice (plan_mesh only factors
    power-of-two device counts, so a 3-host 24-chip placement is not
    claimable even though the hosts exist). Shared by the scheduler and
    the /evaluate API so the preview claim always matches what placement
    would actually do.
    """
    from gpustack_tpu.policies.topology import tileable_counts

    max_single = max(w.total_chips for w in workers)
    max_chips = max_single
    allowed: set = set()
    for w in workers:
        sl = w.status.slice
        allowed |= tileable_counts(
            sl.topology if sl else "", w.total_chips
        )
    if distributable:
        domains: dict = {}
        for w in workers:
            sl = w.status.slice
            if sl and sl.ici_domain:
                domains[sl.ici_domain] = (
                    domains.get(sl.ici_domain, 0) + w.total_chips
                )
        if domains:
            max_chips = max(max_chips, max(domains.values()))
        for w in workers:
            sl = w.status.slice
            if sl and sl.ici_domain and w.total_chips:
                n = w.total_chips * 2
                while n <= max_chips:
                    allowed.add(n)
                    n *= 2
    return max_chips, allowed


def chips_for_claim(
    evaluation: ModelEvaluation,
    hbm_per_chip: int,
    max_chips: int,
    long_context: bool = False,
    explicit_plan: str = "",
    explicit_chips: int = 0,
    allowed_counts: Optional[set] = None,
) -> Optional[ComputedResourceClaim]:
    """Pick chips-per-replica (power of two) and a mesh plan that fits.

    Returns None when the model cannot fit on ``max_chips`` chips.
    Mirrors the reference's candidate ladder (manual → 1 GPU → multi-GPU →
    multi-worker, vllm_resource_fit_selector.py:315-341) but in chip space:
    the smallest power-of-two chip count whose per-chip share fits HBM.

    ``allowed_counts`` (from policies/topology.tileable_counts over the
    eligible fleet) restricts the ladder to chip counts that actually
    tile some worker's ICI mesh — a 2-chip claim on a 2x4 v5e host is
    unplaceable and must be bumped to 4, not discovered to be
    unschedulable later.
    """
    usable = int(hbm_per_chip * HBM_UTILIZATION)
    if usable <= 0:
        return None
    cfg = evaluation.config

    if explicit_plan:
        plan = MeshPlan.parse(explicit_plan)
        chips = plan.chips
        per_chip = evaluation.total_bytes // chips
        if chips <= max_chips and per_chip <= usable:
            return ComputedResourceClaim(
                chips=chips,
                mesh_plan=str(plan),
                hbm_bytes_per_chip=per_chip + _per_chip_overhead(evaluation, chips),
                weight_bytes=evaluation.weight_bytes,
                kv_cache_bytes=evaluation.kv_cache_bytes,
            )
        return None

    start = explicit_chips or 1
    chips = max(1, start)
    while chips <= max_chips:
        if (
            allowed_counts is not None
            and chips not in allowed_counts
            and not explicit_chips
        ):
            chips *= 2
            continue
        # weights and KV shard across chips; overhead replicates
        per_chip = (
            (evaluation.weight_bytes + evaluation.kv_cache_bytes) // chips
            + evaluation.overhead_bytes
        )
        if per_chip <= usable:
            plan = plan_mesh(
                chips,
                num_kv_heads=cfg.num_kv_heads,
                num_experts=cfg.num_experts,
                long_context=long_context,
            )
            return ComputedResourceClaim(
                chips=chips,
                mesh_plan=str(plan),
                hbm_bytes_per_chip=per_chip,
                weight_bytes=evaluation.weight_bytes,
                kv_cache_bytes=evaluation.kv_cache_bytes,
            )
        if explicit_chips:
            return None  # user pinned the count; it doesn't fit
        chips *= 2
    return None


def _per_chip_overhead(evaluation: ModelEvaluation, chips: int) -> int:
    return evaluation.overhead_bytes
