"""Scheduler loop: PENDING instances → evaluated → placed → SCHEDULED.

Reference flow parity (gpustack/scheduler/scheduler.py:100-405): event-
driven on instance creation + periodic full scan; per instance:
ANALYZING (resource evaluation) → candidate build (filters → selector) →
scoring → placement written onto the instance. Stuck ANALYZING/SCHEDULED
instances are retried after a timeout (reference scheduler.py:261-298).
"""

from __future__ import annotations

import asyncio
import datetime
import logging
from typing import Optional

from gpustack_tpu.policies import (
    build_candidates,
    filter_workers,
    score_candidates,
)
from gpustack_tpu.scheduler.calculator import (
    EvaluationError,
    chips_for_claim,
    evaluate_model,
)
from gpustack_tpu.schemas import (
    DevInstance,
    DevInstanceState,
    Model,
    ModelFile,
    ModelInstance,
    ModelInstanceState,
    Worker,
)
from gpustack_tpu.server.bus import EventType

logger = logging.getLogger(__name__)

RESCHEDULE_STUCK_AFTER = 180.0  # reference scheduler.py:261-298 (3 min)

# jax.distributed coordinator port band (reference port-band logic:
# serve_manager.py:1456-1508). Ports are claimed in PAIRS (coordinator +
# command channel), so the band holds RANGE/2 concurrent multi-host
# instances per leader — 4096 keeps the 2000-instance headroom the
# uniqueness test pins.
COORDINATOR_PORT_BASE = 41000
COORDINATOR_PORT_RANGE = 4096


def pick_coordinator_port(
    instances, leader_worker_id: int, exclude_instance_id: int
) -> int:
    """Lowest even-aligned band port whose PAIR is not claimed by another
    instance on this leader. Ports are allocated in pairs: ``p`` is the
    jax.distributed coordinator, ``p + 1`` the leader→follower command
    channel (engine/multihost.py) — pairing fences both with one claim.

    Returns 0 when the band is exhausted. The leader host additionally
    bind-probes both ports before spawning (serve_manager) — this
    function fences only DB-known claims.
    """
    used = set()
    for i in instances:
        if (
            i.coordinator_address
            and i.worker_id == leader_worker_id
            and i.id != exclude_instance_id
        ):
            p = int(i.coordinator_address.rsplit(":", 1)[1])
            used.update((p, p + 1))
    for p in range(
        COORDINATOR_PORT_BASE,
        COORDINATOR_PORT_BASE + COORDINATOR_PORT_RANGE,
        2,
    ):
        if p not in used and p + 1 not in used:
            return p
    return 0


class Scheduler:
    def __init__(self, scan_interval: float = 30.0):
        self.scan_interval = scan_interval
        self._task: Optional[asyncio.Task] = None
        self._scan_task: Optional[asyncio.Task] = None
        self._dev_task: Optional[asyncio.Task] = None
        self._queue: asyncio.Queue = asyncio.Queue()
        # serialize placements: the watch task and periodic scan both call
        # _schedule_one; unserialized, two multi-host placements on one
        # leader could read the same instance snapshot and pick the same
        # coordinator port
        self._place_lock = asyncio.Lock()

    def start(self) -> None:
        self._task = asyncio.create_task(self._watch(), name="sched-watch")
        self._scan_task = asyncio.create_task(
            self._periodic_scan(), name="sched-scan"
        )
        self._dev_task = asyncio.create_task(
            self._watch_dev(), name="sched-watch-dev"
        )

    def stop(self) -> None:
        for t in (self._task, self._scan_task, self._dev_task):
            if t:
                t.cancel()

    async def _watch(self) -> None:
        while True:
            agen = ModelInstance.subscribe(send_initial=True, heartbeat=30.0)
            try:
                async for event in agen:
                    if event.type == EventType.RESYNC:
                        break
                    if event.type not in (
                        EventType.CREATED, EventType.UPDATED
                    ):
                        continue
                    data = event.data or {}
                    if data.get("state") != ModelInstanceState.PENDING.value:
                        continue
                    # An ANALYZING→PENDING flip is our own "unschedulable"
                    # backoff — retried by the periodic scan, not the watch
                    # (otherwise this would spin hot).
                    changes = event.changes or {}
                    if changes.get("state", (None,))[0] == (
                        ModelInstanceState.ANALYZING.value
                    ):
                        continue
                    await self._schedule_one_logged(event.id)
            except asyncio.CancelledError:
                await agen.aclose()
                raise
            finally:
                await agen.aclose()

    async def _schedule_one_logged(self, instance_id: int) -> None:
        """A placement bug must mark ONE instance ERROR — never kill the
        watch task silently (which would freeze all future scheduling)."""
        try:
            await self._schedule_one(instance_id)
        except Exception as e:
            logger.exception("scheduling instance %d failed", instance_id)
            inst = await ModelInstance.get(instance_id)
            if inst is not None:
                await inst.update(
                    state=ModelInstanceState.ERROR,
                    state_message=f"scheduler error: {e}",
                )

    async def _periodic_scan(self) -> None:
        while True:
            await asyncio.sleep(self.scan_interval)
            try:
                await self._scan()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("scheduler scan failed")

    async def _scan(self) -> None:
        now = datetime.datetime.now(datetime.timezone.utc)
        for dev in await DevInstance.filter(
            state=DevInstanceState.PENDING
        ):
            await self._schedule_dev_logged(dev.id)
        for inst in await ModelInstance.all():
            if inst.state == ModelInstanceState.PENDING:
                await self._schedule_one_logged(inst.id)
            elif inst.state in (
                ModelInstanceState.ANALYZING,
                ModelInstanceState.SCHEDULED,
            ):
                # stuck? (worker never picked it up / we crashed mid-flight)
                try:
                    updated = datetime.datetime.fromisoformat(
                        inst.updated_at
                    )
                except ValueError:
                    continue
                if (now - updated).total_seconds() > RESCHEDULE_STUCK_AFTER:
                    logger.warning(
                        "instance %s stuck in %s; rescheduling",
                        inst.name, inst.state.value,
                    )
                    await inst.update(
                        state=ModelInstanceState.PENDING,
                        worker_id=None,
                        chip_indexes=[],
                        subordinate_workers=[],
                        state_message="rescheduled after timeout",
                    )

    # ------------------------------------------------------------------

    async def _schedule_one(self, instance_id: int) -> None:
        async with self._place_lock:
            await self._schedule_one_locked(instance_id)

    async def _schedule_one_locked(self, instance_id: int) -> None:
        inst = await ModelInstance.get(instance_id)
        if inst is None or inst.state != ModelInstanceState.PENDING:
            return
        model = await Model.get(inst.model_id)
        if model is None:
            await inst.update(
                state=ModelInstanceState.ERROR,
                state_message="model no longer exists",
            )
            return
        await inst.update(state=ModelInstanceState.ANALYZING)

        try:
            # evaluate in an executor: it may shell out to model-meta on a
            # large checkpoint dir — never block the control-plane loop.
            # The instance's disaggregated role is a KV-sizing dimension
            # (prefill replicas plan against a bounded handoff buffer,
            # not the full continuous batch), so chips-per-replica is
            # derived from the ROLE's KV fit.
            evaluation = await asyncio.get_running_loop().run_in_executor(
                None, evaluate_model, model, inst.role
            )
        except EvaluationError as e:
            await inst.update(
                state=ModelInstanceState.ERROR, state_message=str(e)
            )
            return

        workers = await Worker.all()
        eligible, drop_reasons = filter_workers(workers, model)
        if not eligible:
            await self._unschedulable(
                inst, f"no eligible workers ({'; '.join(drop_reasons[:4])})"
            )
            return

        # chip budget: largest single worker, or whole slices when
        # distributable (shared with the /evaluate API)
        from gpustack_tpu.scheduler.calculator import fleet_chip_budget

        max_chips, allowed_counts = fleet_chip_budget(
            eligible, model.distributable
        )

        hbm = min(
            (w.hbm_per_chip for w in eligible if w.hbm_per_chip), default=0
        )
        claim = chips_for_claim(
            evaluation,
            hbm_per_chip=hbm,
            max_chips=max_chips,
            long_context=model.max_seq_len >= 16384,
            explicit_plan=model.mesh_plan,
            explicit_chips=model.chips_per_replica,
            allowed_counts=allowed_counts,
        )
        if claim is None:
            gib = evaluation.total_bytes / 2**30
            await self._unschedulable(
                inst,
                f"model needs ~{gib:.1f} GiB; no fit within {max_chips} "
                f"chips of {hbm / 2**30:.0f} GiB HBM",
            )
            return

        instances = await ModelInstance.all()
        # dev instances hold chips too (reference gpu_instances consume
        # scheduled capacity alongside model workloads)
        claims = list(instances) + list(await DevInstance.all())
        candidates = build_candidates(model, claim, eligible, claims)
        if not candidates:
            await self._unschedulable(
                inst,
                f"needs {claim.chips} chips; no worker has a free aligned "
                f"ICI sub-slice of that size (free chips may be "
                f"fragmented or the count may not tile the topology)",
            )
            return
        model_files = await ModelFile.all()
        best = score_candidates(candidates, model, instances, model_files)[0]

        # multi-host: fix the jax.distributed rendezvous point on the
        # leader (replaces the reference's Ray/TCP-store port plumbing,
        # serve_manager.py:1456-1508). Ports come from a fenced band with
        # DB-known collisions excluded — id % 1000 would collide across
        # 1000 instances; the leader additionally bind-probes before
        # spawning (serve_manager).
        coordinator = ""
        if best.subordinates:
            port = pick_coordinator_port(
                instances, best.worker.id, inst.id
            )
            if not port:
                await self._unschedulable(
                    inst,
                    "no free coordinator ports on leader "
                    f"{best.worker.name}",
                )
                return
            coordinator = f"{best.worker.ip or '127.0.0.1'}:{port}"
        await inst.update(
            state=ModelInstanceState.SCHEDULED,
            worker_id=best.worker.id,
            worker_name=best.worker.name,
            worker_ip=best.worker.ip,
            chip_indexes=best.chip_indexes,
            computed_resource_claim=claim,
            subordinate_workers=best.subordinates,
            coordinator_address=coordinator,
            state_message="",
        )
        logger.info(
            "scheduled %s onto %s chips=%s mesh=%s%s",
            inst.name, best.worker.name, best.chip_indexes, claim.mesh_plan,
            f" +{len(best.subordinates)} subordinate hosts"
            if best.subordinates else "",
        )

    async def _unschedulable(self, inst: ModelInstance, msg: str) -> None:
        logger.warning("instance %s unschedulable: %s", inst.name, msg)
        await inst.update(
            state=ModelInstanceState.PENDING, state_message=msg
        )

    # -- dev instances (reference gpu_instances placement role) ----------

    async def _watch_dev(self) -> None:
        while True:
            agen = DevInstance.subscribe(send_initial=True, heartbeat=30.0)
            try:
                async for event in agen:
                    if event.type == EventType.RESYNC:
                        break
                    if event.type not in (
                        EventType.CREATED, EventType.UPDATED
                    ):
                        continue
                    data = event.data or {}
                    if data.get("state") != DevInstanceState.PENDING.value:
                        continue
                    await self._schedule_dev_logged(event.id)
            except asyncio.CancelledError:
                await agen.aclose()
                raise
            finally:
                await agen.aclose()

    async def _schedule_dev_logged(self, dev_id: int) -> None:
        try:
            async with self._place_lock:
                await self._schedule_dev_locked(dev_id)
        except Exception as e:
            logger.exception("scheduling dev instance %d failed", dev_id)
            dev = await DevInstance.get(dev_id)
            if dev is not None:
                await dev.update(
                    state=DevInstanceState.ERROR,
                    state_message=f"scheduler error: {e}",
                )

    async def _schedule_dev_locked(self, dev_id: int) -> None:
        from gpustack_tpu.policies.allocatable import (
            worker_allocatable_chips,
        )
        from gpustack_tpu.policies.topology import allocate_subslice
        from gpustack_tpu.schemas import WorkerState

        dev = await DevInstance.get(dev_id)
        if dev is None or dev.state != DevInstanceState.PENDING:
            return
        claims = list(await ModelInstance.all()) + list(
            await DevInstance.all()
        )
        best = None
        best_free = -1
        for w in await Worker.all():
            if w.state != WorkerState.READY:
                continue
            if dev.cluster_id and w.cluster_id != dev.cluster_id:
                continue
            free = worker_allocatable_chips(w, claims)
            sl = w.status.slice
            chips = allocate_subslice(
                sl.topology if sl else "",
                w.total_chips,
                free,
                dev.chips,
            )
            # spread: prefer the worker with the most free chips left
            if chips is not None and len(free) > best_free:
                best, best_free = (w, chips), len(free)
        if best is None:
            await dev.update(
                state_message=(
                    f"no worker has a free aligned {dev.chips}-chip "
                    "sub-slice; retried on the next scan"
                )
            )
            return
        worker, chips = best
        await dev.update(
            state=DevInstanceState.SCHEDULED,
            worker_id=worker.id,
            worker_name=worker.name,
            chip_indexes=chips,
            state_message="",
        )
        logger.info(
            "scheduled dev instance %s onto %s chips=%s",
            dev.name, worker.name, chips,
        )
