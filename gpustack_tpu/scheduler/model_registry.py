"""Model registry: resolved architecture → categories.

Reference parity: scheduler/model_registry.py detect_model_type — the
reference pins ~500 architecture names copied from the vLLM registry;
we classify structurally instead (HF architecture-string conventions +
config hints), with small exception sets where the conventions collide.
Categories drive backend selection (audio vs image vs LLM engine),
catalog filtering, and UI grouping; users can still override by setting
categories explicitly.
"""

from __future__ import annotations

from typing import List, Optional

from gpustack_tpu.schemas import Model

# Encoder families whose exports are embedding models even without an
# "Embedding" marker in the class name.
_ENCODER_FAMILIES = (
    "Bert",            # BertModel, ModernBertModel, NomicBertModel, ...
    "Roberta",
    "Electra",
    "MPNet",
    "Deberta",
    "MiniLM",
    "Gte",
    "Jina",
    "CLIP",            # CLIPModel text/vision embedders
)

# "*Model" exports that are decoder LLM entries, not embedding encoders
# (the reference's text-generation table lists these explicitly).
_CAUSAL_MODEL_EXCEPTIONS = {
    "ChatGLMModel",
    "AquilaModel",
}

# Decoder families whose headless "*Model" exports (LlamaModel,
# Qwen2Model, ...) are conventionally embedding checkpoints (gte-Qwen2,
# e5-mistral).  The heuristic is restricted to these stems so an
# unrecognized "<New>Model" arch falls through to [] instead of being
# silently steered to the embedding backend.
_HEADLESS_EMBED_FAMILIES = (
    "Llama",
    "Qwen",
    "Mistral",
    "Gemma",
    "Phi",
    "InternLM",
    "Starcoder",
)

_TTS_MARKERS = ("TextToSpeech", "Tts", "TTS", "Vits", "Bark", "CosyVoice")

_IMAGE_MARKERS = (
    "StableDiffusion", "Flux", "PixArt", "Sana", "Lumina", "Kandinsky",
)

_MULTIMODAL_MARKERS = (
    "VLForConditionalGeneration",
    "VLChatModel",
    "Llava",
    "InternVL",
    "Vision2Seq",
    "Idefics",
    "Paligemma",
    "Phi3V",
    "Pixtral",
)


def classify_architectures(
    architectures: List[str], model_type: str = ""
) -> List[str]:
    """HF ``architectures`` + ``model_type`` → category list.

    Returns [] when nothing matches (caller decides the fallback).
    Mirrors reference detect_model_type/is_multimodal_model
    (scheduler/model_registry.py:439,463) without its copied tables.
    """
    archs = [a for a in (architectures or []) if a]
    if model_type == "whisper" or any("Whisper" in a for a in archs):
        return ["audio", "speech-to-text"]
    if model_type in ("vits", "bark") or any(
        m in a for a in archs for m in _TTS_MARKERS
    ):
        return ["audio", "text-to-speech"]
    if any(m in a for a in archs for m in _IMAGE_MARKERS):
        return ["image", "text-to-image"]
    for a in archs:
        # cross-encoders ship as sequence classifiers
        if a.endswith("ForSequenceClassification") or "Rerank" in a:
            return ["reranker"]
    # multimodal chat models before the embedding pass: several end in
    # "Model" (InternVLChatModel) and would hit its catch-all
    if any(m in a for a in archs for m in _MULTIMODAL_MARKERS):
        return ["llm", "multimodal"]
    for a in archs:
        if "Embedding" in a or a.endswith("ForMaskedLM"):
            return ["embedding"]
        if any(f in a for f in _ENCODER_FAMILIES):
            return ["embedding"]
        # decoder-as-encoder exports: Qwen2Model, LlamaModel, MistralModel
        # — the headless variant of a known causal family is an embedder;
        # unknown "*Model" names fall through (caller keeps user category)
        if (
            a.endswith("Model")
            and a not in _CAUSAL_MODEL_EXCEPTIONS
            and any(f in a for f in _HEADLESS_EMBED_FAMILIES)
        ):
            return ["embedding"]
    for a in archs:
        if a in _CAUSAL_MODEL_EXCEPTIONS or a.endswith(
            ("ForCausalLM", "ForConditionalGeneration", "LMHeadModel")
        ):
            return ["llm"]
    return []


def detect_categories(model: Model) -> List[str]:
    """Best-effort categories from the model's source; empty list when
    the source cannot be resolved (leave user input alone).

    Architecture strings are the primary signal (they classify even
    checkpoints our engine can't serve yet); the resolved config adds
    engine-level tags (moe / long-context) and covers presets.
    """
    from gpustack_tpu.models.diffusion import DiffusionConfig
    from gpustack_tpu.models.whisper import WhisperConfig
    from gpustack_tpu.scheduler.calculator import (
        EvaluationError,
        resolve_model_config,
        resolve_raw_config,
    )

    from gpustack_tpu.models.vlm import VLM_PRESETS

    if model.preset in VLM_PRESETS:
        return ["llm", "multimodal"]
    raw: Optional[dict] = None
    try:
        raw = resolve_raw_config(model)
    except EvaluationError:
        return []
    cats: List[str] = []
    if raw is not None:
        cats = classify_architectures(
            raw.get("architectures") or [], raw.get("model_type") or ""
        )
        if cats and cats[0] != "llm":
            return cats

    try:
        cfg = resolve_model_config(model, raw=raw)
    except EvaluationError:
        return cats
    if isinstance(cfg, WhisperConfig):
        return ["audio", "speech-to-text"]
    from gpustack_tpu.models.tts import TTSConfig

    if isinstance(cfg, TTSConfig):
        return ["audio", "text-to-speech"]
    if isinstance(cfg, DiffusionConfig):
        return ["image", "text-to-image"]
    out = cats or ["llm"]
    if getattr(cfg, "num_experts", 0):
        out.append("moe")
    if getattr(cfg, "max_position_embeddings", 0) >= 32768:
        out.append("long-context")
    return out
