"""Model registry: resolved architecture → categories.

Reference parity: scheduler/model_registry.py detect_model_type (476 LoC
of per-architecture tables) — compressed to the signals our engine
actually dispatches on. Categories drive backend selection (audio vs LLM
engine), catalog filtering, and UI grouping; users can still override by
setting categories explicitly.
"""

from __future__ import annotations

from typing import List

from gpustack_tpu.schemas import Model


def detect_categories(model: Model) -> List[str]:
    """Best-effort categories from the model's resolved config; empty
    list when the source cannot be resolved (leave user input alone)."""
    from gpustack_tpu.models.diffusion import DiffusionConfig
    from gpustack_tpu.models.whisper import WhisperConfig
    from gpustack_tpu.scheduler.calculator import (
        EvaluationError,
        resolve_model_config,
    )

    try:
        cfg = resolve_model_config(model)
    except EvaluationError:
        return []
    if isinstance(cfg, WhisperConfig):
        return ["audio", "speech-to-text"]
    if isinstance(cfg, DiffusionConfig):
        return ["image", "text-to-image"]
    out = ["llm"]
    if getattr(cfg, "num_experts", 0):
        out.append("moe")
    if getattr(cfg, "max_position_embeddings", 0) >= 32768:
        out.append("long-context")
    return out
