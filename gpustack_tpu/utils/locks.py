"""Heartbeat soft file locks for cross-process download coordination
(reference gpustack/utils/locks.py HeartbeatSoftFileLock semantics: a lock
file whose mtime is refreshed while held; stale locks are stolen)."""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Optional

logger = logging.getLogger(__name__)


class SoftFileLock:
    def __init__(
        self,
        path: str,
        stale_after: float = 60.0,
        heartbeat: float = 10.0,
    ):
        self.path = path
        self.stale_after = stale_after
        self.heartbeat = heartbeat
        self._held = False
        self._hb_task: Optional[asyncio.Task] = None

    async def acquire(self, timeout: float = 3600.0) -> None:
        deadline = time.monotonic() + timeout
        while True:
            try:
                fd = os.open(
                    self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY
                )
                os.write(fd, str(os.getpid()).encode())
                os.close(fd)
                self._held = True
                self._hb_task = asyncio.create_task(self._heartbeat_loop())
                return
            except FileExistsError:
                try:
                    st = os.stat(self.path)
                except OSError:
                    continue  # holder just released; retry immediately
                age = time.time() - st.st_mtime
                if age > self.stale_after:
                    # Narrow the steal race: re-stat and only unlink if the
                    # file is still the same stale one (a concurrent
                    # stealer may have already replaced it with a fresh,
                    # actively-heartbeated lock).
                    try:
                        st2 = os.stat(self.path)
                        if (
                            st2.st_ino == st.st_ino
                            and st2.st_mtime == st.st_mtime
                        ):
                            logger.warning(
                                "stealing stale lock %s (age %.0fs)",
                                self.path, age,
                            )
                            os.unlink(self.path)
                    except OSError:
                        pass
                    continue
            if time.monotonic() > deadline:
                raise TimeoutError(f"could not acquire lock {self.path}")
            await asyncio.sleep(1.0)

    async def _heartbeat_loop(self) -> None:
        while self._held:
            await asyncio.sleep(self.heartbeat)
            try:
                os.utime(self.path)
            except OSError:
                return

    def release(self) -> None:
        self._held = False
        if self._hb_task:
            self._hb_task.cancel()
        try:
            os.unlink(self.path)
        except OSError:
            pass

    async def __aenter__(self) -> "SoftFileLock":
        await self.acquire()
        return self

    async def __aexit__(self, *exc) -> None:
        self.release()
