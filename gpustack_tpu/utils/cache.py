"""TTL cache + ``locked_cached`` decorator (reference server/cache.py
TTL cache + locked_cached: expensive lookups computed once per TTL with
concurrent callers coalesced onto one in-flight computation).
"""

from __future__ import annotations

import asyncio
import functools
import time
from typing import Any, Awaitable, Callable, Dict, Hashable, Optional, Tuple


class TTLCache:
    def __init__(self, ttl: float = 30.0, max_entries: int = 1024):
        self.ttl = ttl
        self.max_entries = max_entries
        self._data: Dict[Hashable, Tuple[float, Any]] = {}

    def get(self, key: Hashable) -> Optional[Any]:
        entry = self._data.get(key)
        if entry is None:
            return None
        expires, value = entry
        if time.monotonic() >= expires:
            del self._data[key]
            return None
        return value

    def set(self, key: Hashable, value: Any) -> None:
        if len(self._data) >= self.max_entries:
            # drop expired first; then oldest-expiring
            now = time.monotonic()
            for k in [
                k for k, (exp, _) in self._data.items() if exp <= now
            ]:
                del self._data[k]
            while len(self._data) >= self.max_entries:
                oldest = min(
                    self._data, key=lambda k: self._data[k][0]
                )
                del self._data[oldest]
        self._data[key] = (time.monotonic() + self.ttl, value)

    def invalidate(self, key: Hashable = None) -> None:
        if key is None:
            self._data.clear()
        else:
            self._data.pop(key, None)

    def __len__(self) -> int:
        return len(self._data)


def locked_cached(ttl: float = 30.0, max_entries: int = 1024):
    """Async memoization with TTL; concurrent callers for the same key
    share ONE in-flight computation (a thundering herd of identical
    expensive lookups — catalog fetches, HF config probes — collapses to
    a single call)."""

    def decorator(fn: Callable[..., Awaitable[Any]]):
        cache = TTLCache(ttl=ttl, max_entries=max_entries)
        locks: Dict[Hashable, asyncio.Lock] = {}

        @functools.wraps(fn)
        async def wrapper(*args, **kwargs):
            key = (args, tuple(sorted(kwargs.items())))
            hit = cache.get(key)
            if hit is not None:
                return hit
            lock = locks.setdefault(key, asyncio.Lock())
            async with lock:
                hit = cache.get(key)          # filled while we waited?
                if hit is not None:
                    return hit
                value = await fn(*args, **kwargs)
                if value is not None:
                    cache.set(key, value)
                return value

        wrapper.cache = cache
        return wrapper

    return decorator
