"""Slow-call tracing (reference utils/profiling.py time_decorator +
the per-minute DB query counter, server/init_db.py::get_query_count).

``timed`` logs any call slower than its threshold; ``CallStats``
accumulates per-name counters a /metrics exporter or debug endpoint can
read.
"""

from __future__ import annotations

import functools
import logging
import threading
import time
from typing import Dict

logger = logging.getLogger(__name__)


class CallStats:
    """Thread-safe per-name call counters (count, total seconds, max)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stats: Dict[str, Dict[str, float]] = {}

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            s = self._stats.setdefault(
                name, {"count": 0, "total_s": 0.0, "max_s": 0.0}
            )
            s["count"] += 1
            s["total_s"] += seconds
            s["max_s"] = max(s["max_s"], seconds)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._stats.items()}


STATS = CallStats()


def timed(threshold_s: float = 1.0, name: str = ""):
    """Decorator (sync or async): record call stats; warn when a call
    exceeds ``threshold_s``."""

    def decorator(fn):
        label = name or f"{fn.__module__}.{fn.__qualname__}"

        def finish(start: float) -> None:
            elapsed = time.monotonic() - start
            STATS.record(label, elapsed)
            if elapsed > threshold_s:
                logger.warning(
                    "slow call: %s took %.2fs (threshold %.2fs)",
                    label, elapsed, threshold_s,
                )

        if _is_coroutine(fn):
            @functools.wraps(fn)
            async def async_wrapper(*args, **kwargs):
                start = time.monotonic()
                try:
                    return await fn(*args, **kwargs)
                finally:
                    finish(start)

            return async_wrapper

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            start = time.monotonic()
            try:
                return fn(*args, **kwargs)
            finally:
                finish(start)

        return wrapper

    return decorator


def _is_coroutine(fn) -> bool:
    import asyncio

    return asyncio.iscoroutinefunction(fn)
