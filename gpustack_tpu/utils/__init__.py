"""Shared utilities."""
