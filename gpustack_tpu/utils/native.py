"""Bridges to the in-repo native (C++) tools under native/bin.

model-meta: exact checkpoint byte accounting for the scheduler (replaces
the reference's gguf-parser shell-outs, scheduler/calculator.py:550-566).
sysinfo: host probe JSON (replaces the fastfetch dependency).
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def find_tool(name: str) -> Optional[str]:
    """Locate a native tool: $GPUSTACK_TPU_NATIVE_BIN, repo build dir,
    then PATH."""
    override = os.environ.get("GPUSTACK_TPU_NATIVE_BIN")
    candidates = []
    if override:
        candidates.append(os.path.join(override, name))
    candidates.append(os.path.join(_REPO_ROOT, "native", "bin", name))
    for path in candidates:
        if os.path.isfile(path) and os.access(path, os.X_OK):
            return path
    from shutil import which

    return which(name)


def run_model_meta(target: str) -> Optional[Dict[str, Any]]:
    """Run model-meta on a checkpoint dir/file; None when unavailable or
    the target has no parseable checkpoint."""
    tool = find_tool("model-meta")
    if tool is None:
        return None
    try:
        out = subprocess.run(
            [tool, target], capture_output=True, timeout=60, check=False
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.warning("model-meta failed: %s", e)
        return None
    if out.returncode != 0:
        logger.debug(
            "model-meta(%s) rc=%d: %s",
            target, out.returncode, out.stderr.decode()[:200],
        )
        return None
    try:
        return json.loads(out.stdout)
    except json.JSONDecodeError:
        logger.warning("model-meta produced invalid JSON")
        return None


def run_sysinfo() -> Optional[Dict[str, Any]]:
    tool = find_tool("sysinfo")
    if tool is None:
        return None
    try:
        out = subprocess.run(
            [tool], capture_output=True, timeout=10, check=False
        )
        if out.returncode != 0:
            return None
        return json.loads(out.stdout)
    except (OSError, subprocess.TimeoutExpired, json.JSONDecodeError):
        return None
