"""Coalescing work queue + exponential backoff.

Reference parity: server/workqueue.py (WorkQueue at :130 +
ExponentialBackoff) — reconcilers enqueue keys, duplicate keys coalesce
while queued, failed items re-enqueue with capped exponential delay.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Awaitable, Callable, Dict, Hashable, Optional, Set

logger = logging.getLogger(__name__)


class ExponentialBackoff:
    """Per-key capped exponential backoff with jitter."""

    def __init__(
        self,
        base: float = 1.0,
        cap: float = 300.0,
        jitter: float = 0.1,
    ):
        self.base = base
        self.cap = cap
        self.jitter = jitter
        self._failures: Dict[Hashable, int] = {}

    def next_delay(self, key: Hashable) -> float:
        n = self._failures.get(key, 0)
        self._failures[key] = n + 1
        delay = min(self.cap, self.base * (2 ** n))
        return delay * (1 + random.uniform(-self.jitter, self.jitter))

    def reset(self, key: Hashable) -> None:
        self._failures.pop(key, None)

    def failures(self, key: Hashable) -> int:
        return self._failures.get(key, 0)


class WorkQueue:
    """Keys in, handler out; duplicates coalesce while queued.

    ``add(key)`` is idempotent while the key waits; a key re-added
    during its own processing is processed again afterwards (level
    triggering, not edge). Handler failures re-enqueue the key after an
    ExponentialBackoff delay; success resets the key's backoff.
    """

    def __init__(
        self,
        handler: Callable[[Hashable], Awaitable[None]],
        *,
        backoff: Optional[ExponentialBackoff] = None,
        name: str = "workqueue",
    ):
        self.handler = handler
        self.backoff = backoff or ExponentialBackoff()
        self.name = name
        self._queue: asyncio.Queue = asyncio.Queue()
        self._queued: Set[Hashable] = set()
        self._processing: Set[Hashable] = set()
        self._dirty: Set[Hashable] = set()
        self._task: Optional[asyncio.Task] = None
        self.processed = 0
        self.retried = 0

    def add(self, key: Hashable) -> None:
        if key in self._processing:
            # level-triggered: reprocess after the current run finishes
            self._dirty.add(key)
            return
        if key in self._queued:
            return
        self._queued.add(key)
        self._queue.put_nowait(key)

    def __len__(self) -> int:
        return len(self._queued)

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(
                self._loop(), name=self.name
            )

    def stop(self) -> None:
        if self._task:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            key = await self._queue.get()
            self._queued.discard(key)
            self._processing.add(key)
            try:
                await self.handler(key)
                self.backoff.reset(key)
                self.processed += 1
            except asyncio.CancelledError:
                raise
            except Exception:
                delay = self.backoff.next_delay(key)
                self.retried += 1
                logger.exception(
                    "%s: handler failed for %r; retry in %.1fs",
                    self.name, key, delay,
                )
                asyncio.get_running_loop().call_later(
                    delay, self.add, key
                )
            finally:
                self._processing.discard(key)
                if key in self._dirty:
                    self._dirty.discard(key)
                    self.add(key)
