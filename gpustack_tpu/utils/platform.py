"""Accelerator platform detection.

JAX can expose a TPU under a platform name other than ``"tpu"`` — a
remote/tunneled PJRT plugin registers its own name while aliasing MLIR
lowering to the TPU rules, so ``jax.default_backend()`` returns the
plugin's name even though Pallas-TPU kernels, bf16 MXU matmuls, and TPU
memory behavior all apply. Kernel selection must treat those platforms
as TPU or the flash path silently degrades to the XLA fallback.

The reference keys the analogous decision off its per-vendor backend
classes (gpustack/worker/backends/*); here one predicate serves every
call site.
"""

from __future__ import annotations

# Platform names that compile through the TPU lowering path.
TPU_PLATFORMS = ("tpu", "axon")


def is_tpu_backend() -> bool:
    """True when the default JAX backend executes on a TPU (directly or
    via a proxying PJRT plugin). Initializes the backend on first call."""
    import jax

    try:
        if jax.default_backend() in TPU_PLATFORMS:
            return True
        return any(d.platform in TPU_PLATFORMS for d in jax.devices())
    except RuntimeError:
        return False


def tpu_chip_count() -> int:
    """Number of visible TPU chips (0 when running on CPU)."""
    import jax

    try:
        return sum(1 for d in jax.devices() if d.platform in TPU_PLATFORMS)
    except RuntimeError:
        return 0
