"""Seeded chaos harness: in-process control plane + protocol-true stub
workers + deterministic fault schedules + convergence invariants.

What runs where:

- The REAL server (``server/server.py`` Server: app, controllers,
  scheduler, worker syncer, instance rescuer) runs in-process on a real
  TCP port with a real sqlite DB under a temp dir.
- ``StubWorker`` agents register over the REAL HTTP API with worker
  tokens and drive the REAL instance lifecycle (scheduled → starting →
  running, crash/restart, drain-retire, post-partition re-drive) the
  same way ``worker/serve_manager.py`` does — but their "engines" are
  in-memory markers, so a full cluster boots in well under a second and
  faults are a flag flip, not a SIGKILL race.
- Faults come from a SEEDED schedule: ``generate_schedule(seed)`` is a
  pure function of the seed, so re-running a seed reproduces the exact
  op sequence (the acceptance property). Supported fault kinds:
    * ``worker_kill``        — agent dies and never returns
    * ``worker_suspend``     — agent pauses (heartbeats + event
                               processing) and resumes later
    * ``heartbeat_blackhole``— liveness channel drops; data path lives
    * ``rpc_delay``/``rpc_drop`` — server→worker control RPCs slowed /
                               failed via the ``worker_request``
                               fault hook (retry tier exercised by a
                               live probe through the real app)
    * ``engine_crash``       — a running engine dies AND the restart
                               crashes mid-STARTING (one-shot)
    * ``server_restart``     — the whole control plane stops and boots
                               again on the same DB, mid-reconcile
- Invariants (testing/invariants.py) are checked continuously mid-run
  (always-scope) by a monitor task plus a transition-legality observer
  on the instance watch stream, and in full (eventual-scope) by
  ``wait_converged``.

CLI (used by ``make chaos``)::

    python -m gpustack_tpu.testing.chaos --classes all --seed 1

runs one seeded schedule per fault class and exits non-zero on any
invariant violation or failed convergence.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import random
import socket
import time
from typing import Dict, List, Optional, Sequence, Tuple

import aiohttp

from gpustack_tpu.client.client import (
    APIError,
    NETWORK_ERRORS,
    ClientSet,
)
from gpustack_tpu.config import Config
from gpustack_tpu.server import worker_request
from gpustack_tpu.server.bus import EventType
from gpustack_tpu.testing import invariants as inv

logger = logging.getLogger(__name__)

CLIENT_ERRORS = NETWORK_ERRORS

FAULT_KINDS = (
    "worker_kill",
    "worker_suspend",
    "heartbeat_blackhole",
    "rpc_delay",
    "rpc_drop",
    "engine_crash",
    "server_restart",
)

# control-plane HA faults: require a multi-server harness (servers>=2,
# shared DB, shrunken GPUSTACK_TPU_HA_TTL) — kept out of FAULT_KINDS so
# the single-server classes never draw an op they can only skip
#   * leader_kill  — the leading server dies mid-reconcile WITHOUT
#                    releasing its lease (SIGKILL shape): the follower
#                    may acquire only after TTL expiry
#   * leader_hang  — the leader's election loop stalls past the TTL
#                    without exiting (event-loop stall shape): a
#                    follower steals the lease, and the hung leader's
#                    still-running writers get FENCED before it
#                    revives, notices, and takes the fatal path
#   * lease_expire — the lease row is force-expired out from under the
#                    leader: fatal on next renewal, successor acquires
#                    with a bumped epoch
HA_FAULT_KINDS = (
    "leader_kill",
    "leader_hang",
    "lease_expire",
)

# fleet-scale control-plane faults (ISSUE 15): multi-server, aimed at
# the election/replication machinery under churn rather than at one
# leader
#   * acquire_storm          — STORM_CONTENDERS ephemeral lease
#     contenders (own Database handles on the shared file) hammer the
#     leadership row for a few TTLs, stealing any lapsed lease and
#     releasing gracefully when the storm ends; judged by the same
#     election-history invariant (one winner per epoch, zero overlap)
#   * rolling_server_restart — every alive server gracefully restarts
#     one-by-one under live stub traffic (the production rolling
#     deploy): leadership hands over without a leaderless gap > 3×TTL,
#     replication resumes, and every committed write survives
SCALE_FAULT_KINDS = (
    "acquire_storm",
    "rolling_server_restart",
)

# contenders per acquire_storm op ("8-way lease storms")
STORM_CONTENDERS = 8

# disaggregated-serving faults: require a role-tagged (prefill/decode)
# deployment — kept out of FAULT_KINDS so plain classes never draw one
#   * kv_handoff_abort — a real proxied request routes through the
#     disaggregated handoff path (decode replica pulling the prefill
#     replica's /kv/export) and the PREFILL worker is killed
#     mid-stream: the decode replica must complete the request from
#     cold, and the cluster must re-converge the role populations
DISAGG_FAULT_KINDS = (
    "kv_handoff_abort",
)

# fleet KV directory faults (ISSUE 16): require a KV-cache-backed
# deployment (host_kv_cache_mb > 0) so cached-prefix-mass routing
# engages — kept out of FAULT_KINDS
#   * directory_stale — the cluster KV directory is poisoned with an
#     entry naming a replica that no longer exists (the scrape raced
#     an instance teardown), then a real proxied chat request whose
#     conversation chain matches the poisoned key is fired: the proxy
#     must count the stale route, degrade to cold routing, and
#     complete the request well inside the handoff timeout — never
#     stall dialing the dead holder
KV_DIRECTORY_FAULT_KINDS = (
    "directory_stale",
)

# tenant QoS faults: require the shrunken model cap + fair watermark
# (TENANT_CFG) so saturation is reachable — kept out of FAULT_KINDS
#   * tenant_flood — two flooding API-key tenants (weights 3:1) hammer
#     the model with more concurrency than its admission slots while a
#     polite higher-priority tenant keeps probing: the weighted-fair
#     layer must 429 the flooders down to their weight shares
#     (fairness judged by invariants.check_fair_shares over the
#     admitted counts) while every polite request succeeds
TENANT_FAULT_KINDS = (
    "tenant_flood",
)

# harness config the noisy-neighbor class needs: a small per-model
# admission pool (saturable by a handful of clients) with the fair
# layer engaged
TENANT_CFG = {
    "model_max_outstanding": 8,
    "tenant_fair_watermark": 0.75,
}

# (name, qos fields) for the synthetic tenants the flood creates; the
# generous rate limit exists so X-RateLimit-* headers ride every
# response (it never binds — the fair-share layer sheds first)
TENANT_SPECS = (
    ("flood-a", dict(weight=3, priority=0, rate_limit_rps=500.0,
                     rate_limit_burst=500)),
    ("flood-b", dict(weight=1, priority=0, rate_limit_rps=500.0,
                     rate_limit_burst=500)),
    ("polite", dict(weight=1, priority=5)),
)

# the acceptance matrix: one seeded schedule per named fault class
FAULT_CLASSES: Dict[str, Tuple[str, ...]] = {
    "worker-kill": ("worker_kill",),
    "heartbeat-blackhole": ("heartbeat_blackhole",),
    "rpc": ("rpc_delay", "rpc_drop"),
    "engine-crash": ("engine_crash",),
    "server-restart": ("server_restart",),
    "ha-failover": HA_FAULT_KINDS,
    "kv-handoff": DISAGG_FAULT_KINDS,
    "kv-directory": KV_DIRECTORY_FAULT_KINDS,
    "noisy-neighbor": TENANT_FAULT_KINDS,
    "acquire-storm": ("acquire_storm",),
    "rolling-server-restart": SCALE_FAULT_KINDS,
    "mixed": FAULT_KINDS,
}

# classes that need more than one server to mean anything
MULTI_SERVER_CLASSES = {
    "ha-failover", "acquire-storm", "rolling-server-restart",
}


@dataclasses.dataclass(frozen=True)
class ChaosOp:
    at: float      # seconds from schedule start
    kind: str      # one of FAULT_KINDS
    target: int    # worker ordinal (ignored by server_restart)
    arg: float     # kind-specific magnitude (delay seconds / jitter)


def generate_schedule(
    seed: int,
    *,
    kinds: Sequence[str] = FAULT_KINDS,
    ops: int = 3,
    workers: int = 2,
    gap: Tuple[float, float] = (0.2, 0.8),
) -> List[ChaosOp]:
    """Pure function of (seed, shape): the same seed ALWAYS yields the
    same schedule — determinism is the contract chaos repros rest on."""
    rng = random.Random(f"gpustack-tpu-chaos-{seed}")
    out: List[ChaosOp] = []
    t = 0.0
    for _ in range(ops):
        t += rng.uniform(*gap)
        out.append(ChaosOp(
            at=round(t, 3),
            kind=kinds[rng.randrange(len(kinds))],
            target=rng.randrange(max(1, workers)),
            arg=round(rng.uniform(0.05, 0.35), 3),
        ))
    return out


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class FaultInjector:
    """Installed as ``worker_request.rpc_fault_hook`` for the run."""

    def __init__(self) -> None:
        self.delay = 0.0
        self.dropping = False
        self.delayed = 0
        self.dropped = 0

    async def __call__(self, worker, method: str, path: str) -> None:
        if self.delay > 0:
            self.delayed += 1
            await asyncio.sleep(self.delay)
        if self.dropping:
            self.dropped += 1
            raise aiohttp.ClientError(
                f"chaos: dropped {method} {path} to worker {worker.id}"
            )


# ---------------------------------------------------------------------------
# Stub worker agent
# ---------------------------------------------------------------------------


class StubWorker:
    """Protocol-true worker agent with in-memory engines.

    Drives instances through the SAME declared lifecycle writes as
    worker/serve_manager.py (states go over the wire as strings; the
    declared writer set lives in schemas/models.py next to
    serve_manager's).
    """

    def __init__(
        self,
        server_url: str,
        registration_token: str,
        name: str,
        *,
        chips: int = 8,
        heartbeat_interval: float = 0.25,
        start_delay: float = 0.08,
        serve_http: bool = True,
    ):
        self.server_url = server_url
        self.registration_token = registration_token
        self.name = name
        self.chips = chips
        self.heartbeat_interval = heartbeat_interval
        self.start_delay = start_delay
        # lite mode (1000+-worker scale suites): skip the per-stub
        # aiohttp reverse-proxy server — the control-plane paths under
        # measurement (registration, heartbeats, status, watch,
        # lifecycle writes) never dial the worker, and a thousand
        # AppRunners would measure the harness, not the server
        self.serve_http = serve_http

        self.worker_id = 0
        self.proxy_secret = ""
        self.client: Optional[ClientSet] = None
        self.port = 0

        self.alive = False
        self.hb_blackholed = False
        self.crash_next_start = False
        self.engines: set = set()       # instance ids with a "live" engine
        # data-plane fault injection: proxied requests to these
        # instance ids answer 500 (a "bad canary" for rollout e2es)
        self.proxy_fail_ids: set = set()
        self.proxied = 0                # data-plane requests served
        # synthetic per-request service time: lets tenant-QoS chaos
        # build real in-flight pressure against the stub engine
        self.proxy_delay = 0.0
        # disaggregated KV handoff simulation: /kv/export streams this
        # many paced chunks (export_delay apart — a kill mid-window
        # drops the connection, the kv_handoff_abort fault); a proxied
        # request carrying X-GPUStack-KV-Source pulls from that URL
        # first and records the outcome ("ok" | "failed-cold")
        self.export_delay = 0.0
        self.export_chunks = 6
        self.export_started = asyncio.Event()
        self.handoff_outcomes: List[str] = []
        self._starting: set = set()
        self._paused = asyncio.Event()  # cleared == suspended
        self._paused.set()
        # same serialization serve_manager has: reconcile's trailing
        # engine-discard sweep must not interleave with another
        # reconcile (watch RESYNC vs periodic vs recovery task)
        self._reconcile_lock = asyncio.Lock()
        self._tasks: List[asyncio.Task] = []
        self._runner: Optional[aiohttp.web.AppRunner] = None
        self._retired_clients: List[ClientSet] = []

    # ---- lifecycle ---------------------------------------------------

    async def start(self) -> None:
        if self.serve_http:
            await self._start_http()
        else:
            self.port = 1  # lite mode: nothing ever dials a stub
        await self._register_and_run()

    async def _start_http(self) -> None:
        from aiohttp import web

        app = web.Application()

        async def healthz(request: web.Request):
            auth = request.headers.get("Authorization", "")
            if auth != f"Bearer {self.proxy_secret}":
                return web.json_response({"error": "forbidden"}, status=403)
            return web.json_response(
                {"ok": True, "engines": len(self.engines)}
            )

        app.router.add_get("/healthz", healthz)

        async def proxy(request: web.Request):
            """Stub of the worker's authenticated reverse proxy
            (worker/server.py /proxy/instances/...): enough of the
            data-plane contract for rollout/autoscaler e2es to drive
            REAL proxied requests through the server's failover path.
            Same auth (full secret, or a KV-scoped token for the
            export path), same stale-routing 404 marker, plus the
            fault-injection hooks (``proxy_fail_ids``,
            ``proxy_delay``)."""
            from gpustack_tpu.api.auth import verify_kv_token

            auth = request.headers.get("Authorization", "")
            iid = int(request.match_info["id"])
            token = (
                auth[7:] if auth.startswith("Bearer ") else ""
            )
            is_export = (
                request.match_info["tail"].rstrip("/") == "kv/export"
            )
            if is_export:
                # export path is kv-token-ONLY (worker/server.py
                # middleware contract): the full proxy secret is
                # rejected here so it never has a reason to travel
                # engine→engine
                if not verify_kv_token(token, self.proxy_secret, iid):
                    return web.json_response(
                        {"error": "forbidden"}, status=403
                    )
            elif token != self.proxy_secret:
                return web.json_response(
                    {"error": "forbidden"}, status=403
                )
            if iid not in self.engines:
                return web.json_response(
                    {"error": "instance not running here"},
                    status=404,
                    headers={
                        "X-GPUStack-Worker": "instance-not-running"
                    },
                )
            self.proxied += 1
            if self.proxy_delay:
                # synthetic service time: in-flight work accumulates,
                # so admission-layer saturation (tenant QoS fair-share
                # windows) is reachable with a handful of clients
                await asyncio.sleep(self.proxy_delay)
            if request.match_info["tail"].rstrip("/") == "kv/export":
                # prefill-role side of a KV handoff: stream paced fake
                # frames. A worker killed mid-window drops the
                # connection mid-stream — exactly the kv_handoff_abort
                # shape the decode side must survive.
                self.export_started.set()
                resp = web.StreamResponse(headers={
                    "Content-Type": "application/x-gpustack-kv"
                })
                await resp.prepare(request)
                for i in range(self.export_chunks):
                    await resp.write(b"GKVX-STUB-%02d" % i)
                    if self.export_delay:
                        await asyncio.sleep(self.export_delay)
                await resp.write_eof()
                return resp
            if iid in self.proxy_fail_ids:
                return web.json_response(
                    {"error": "chaos: injected engine failure"},
                    status=500,
                )
            src = request.headers.get("X-GPUStack-KV-Source", "")
            if src:
                # decode-role side: pull the conversation's blocks from
                # the named peer BEFORE serving — a dead/dying peer
                # degrades to a cold completion, never a failure
                outcome = "ok"
                try:
                    headers = {}
                    src_auth = request.headers.get(
                        "X-GPUStack-KV-Source-Auth", ""
                    )
                    if src_auth:
                        headers["Authorization"] = src_auth
                    async with aiohttp.ClientSession() as http:
                        async with http.post(
                            src,
                            json={"prompt_ids": [], "have": []},
                            headers=headers,
                            timeout=aiohttp.ClientTimeout(total=15),
                        ) as r:
                            if r.status != 200:
                                raise aiohttp.ClientError(
                                    f"peer HTTP {r.status}"
                                )
                            async for _ in r.content.iter_any():
                                pass
                except (
                    aiohttp.ClientError, asyncio.TimeoutError, OSError
                ):
                    outcome = "failed-cold"
                self.handoff_outcomes.append(outcome)
            return web.json_response({
                "id": f"stub-{iid}-{self.proxied}",
                "object": "chat.completion",
                "model": "stub",
                "choices": [{
                    "index": 0,
                    "finish_reason": "stop",
                    "message": {
                        "role": "assistant", "content": "ok",
                    },
                }],
                "usage": {
                    "prompt_tokens": 1,
                    "completion_tokens": 1,
                    "total_tokens": 2,
                },
            })

        app.router.add_post(
            "/proxy/instances/{id:\\d+}/{tail:.*}", proxy
        )
        self._runner = web.AppRunner(app, shutdown_timeout=0.2)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        for sock in site._server.sockets:  # noqa: SLF001 (no public API)
            self.port = sock.getsockname()[1]
            break

    async def _register_and_run(self) -> None:
        anon = ClientSet(self.server_url)
        try:
            deadline = asyncio.get_running_loop().time() + 30.0
            while True:
                try:
                    result = await anon.register_worker({
                        "registration_token": self.registration_token,
                        "name": self.name,
                        "worker_uuid": f"stub-{self.name}",
                        "ip": "127.0.0.1",
                        "port": self.port,
                    })
                    break
                except CLIENT_ERRORS:
                    if asyncio.get_running_loop().time() > deadline:
                        raise
                    await asyncio.sleep(0.2)
        finally:
            await anon.close()
        self.worker_id = result["worker_id"]
        self.proxy_secret = result.get("proxy_secret", "")
        self._token = result["token"]
        self.client = ClientSet(self.server_url, self._token)
        self.alive = True
        await self._post_status()
        self._tasks = [
            asyncio.create_task(
                self._heartbeat_loop(), name=f"{self.name}-hb"
            ),
            asyncio.create_task(
                self._watch_loop(), name=f"{self.name}-watch"
            ),
            asyncio.create_task(
                self._reconcile_loop(), name=f"{self.name}-reconcile"
            ),
        ]

    async def kill(self) -> None:
        """The host dies: no deregistration, no goodbye."""
        self.alive = False
        for t in self._tasks:
            t.cancel()
        self._tasks = []
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        if self.client is not None:
            await self.client.close()
        retired, self._retired_clients = self._retired_clients, []
        for client in retired:
            await client.close()

    async def rebase(self, new_url: str) -> None:
        """Re-point at a surviving HA server (the load balancer a real
        deployment puts in front of the control plane): the worker
        token is a shared-secret JWT, valid against any peer."""
        if not self.alive or new_url == self.server_url:
            return
        self.server_url = new_url
        old_client = self.client
        self.client = ClientSet(new_url, self._token)
        # the watch generator captured the OLD client and would retry
        # against the dead server forever — restart that task only
        for i, task in enumerate(self._tasks):
            if task.get_name() == f"{self.name}-watch":
                task.cancel()
                self._tasks[i] = asyncio.create_task(
                    self._watch_loop(), name=f"{self.name}-watch"
                )
                break
        if old_client is not None:
            # do NOT close yet: the heartbeat/reconcile loops may have
            # an in-flight call on it, and a closed session raises
            # RuntimeError (outside CLIENT_ERRORS) which would KILL the
            # loop task. Requests against the dead server fail as
            # ordinary network errors; the session closes at kill().
            self._retired_clients.append(old_client)

    def suspend(self) -> None:
        self._paused.clear()

    def resume(self) -> None:
        self._paused.set()

    def crash_engine(self) -> None:
        """Kill one engine (if any) and arm a one-shot mid-STARTING
        crash for the next start attempt."""
        self.crash_next_start = True
        if self.engines:
            self.engines.discard(min(self.engines))

    # ---- agent loops -------------------------------------------------

    def _status(self) -> dict:
        return {
            "cpu_count": 8,
            "memory_total_bytes": 16 * 2**30,
            "chips": [
                {"index": i, "chip_type": "v5e", "hbm_bytes": 16 * 2**30}
                for i in range(self.chips)
            ],
            "slice": {
                "topology": f"2x{max(1, self.chips // 2)}",
                "chips_per_host": self.chips,
                "num_hosts": 1,
                "host_index": 0,
            },
        }

    async def _post_status(self) -> None:
        try:
            await self.client.post_status(self.worker_id, self._status())
        except CLIENT_ERRORS as e:
            logger.debug("%s status post failed: %s", self.name, e)

    async def _heartbeat_loop(self) -> None:
        recovery_task: Optional[asyncio.Task] = None
        while self.alive:
            if self._paused.is_set() and not self.hb_blackholed:
                try:
                    resp = await self.client.heartbeat(
                        self.worker_id, timeout=2.0
                    )
                    if resp.get("recovered") and (
                        recovery_task is None or recovery_task.done()
                    ):
                        # mirror worker/worker.py: re-drive parked
                        # instances, but never stall the liveness
                        # signal behind the reconcile (fire-and-forget,
                        # deduped; the level-triggered flag re-arms)
                        recovery_task = asyncio.create_task(
                            self._post_recovery(),
                            name=f"{self.name}-recovery",
                        )
                except CLIENT_ERRORS as e:
                    logger.debug("%s heartbeat failed: %s", self.name, e)
            await asyncio.sleep(self.heartbeat_interval)

    async def _post_recovery(self) -> None:
        await self._post_status()
        try:
            await self.reconcile()
        except CLIENT_ERRORS as e:
            logger.debug("%s recovery reconcile failed: %s", self.name, e)

    async def _watch_loop(self) -> None:
        async for event in self.client.watch(
            "model-instances", retry_delay=0.25
        ):
            if not self.alive:
                return
            await self._paused.wait()
            try:
                await self._handle_event(event)
            except CLIENT_ERRORS as e:
                logger.debug("%s event handling failed: %s", self.name, e)

    async def _reconcile_loop(self) -> None:
        # the periodic safety net a real agent gets from RESYNC +
        # monitor loops, compressed for test time
        while self.alive:
            await asyncio.sleep(max(0.5, self.heartbeat_interval * 3))
            await self._paused.wait()
            try:
                await self.reconcile()
            except CLIENT_ERRORS as e:
                logger.debug("%s reconcile failed: %s", self.name, e)

    async def _handle_event(self, event) -> None:
        if event.type == EventType.RESYNC:
            await self.reconcile()
            return
        if event.type == EventType.HEARTBEAT:
            return
        if event.type == EventType.DELETED:
            self.engines.discard(event.id)
            return
        data = event.data or {}
        if data.get("worker_id") != self.worker_id:
            # moved away from us (reschedule): drop the engine
            self.engines.discard(event.id)
            return
        state = data.get("state")
        if state == "scheduled":
            self._spawn(event.id)
        elif state == "draining":
            await self._retire(event.id)

    # ---- instance lifecycle (serve_manager's writes, stubbed) --------

    def _spawn(self, iid: int) -> None:
        if iid in self._starting or iid in self.engines:
            return
        self._starting.add(iid)

        async def go():
            try:
                await self._start(iid)
            finally:
                self._starting.discard(iid)

        asyncio.create_task(go(), name=f"{self.name}-start-{iid}")

    async def _start(self, iid: int) -> None:
        try:
            raw = await self.client.get("model-instances", iid)
        except CLIENT_ERRORS:
            return
        if raw.get("worker_id") != self.worker_id:
            return
        if raw.get("state") != "scheduled":
            return
        await self._set_state(
            iid, "starting", "stub engine starting",
            port=40000 + (iid % 1000),
        )
        await asyncio.sleep(self.start_delay)
        if not self.alive:
            return
        if self.crash_next_start:
            # the named fault: engine dies MID-STARTING, then the
            # restart_on_error path re-drives (serve_manager._crash)
            self.crash_next_start = False
            await self._set_state(
                iid, "error", "chaos: engine crashed mid-starting"
            )
            await asyncio.sleep(self.start_delay)
            await self._set_state(
                iid, "scheduled", "restart after engine crash",
                restarts=int(raw.get("restarts", 0)) + 1,
            )
            return  # our own watch/reconcile re-drives from SCHEDULED
        self.engines.add(iid)
        await self._set_state(iid, "running", "")

    async def _retire(self, iid: int) -> None:
        self.engines.discard(iid)
        try:
            await self.client.delete("model-instances", iid)
        except CLIENT_ERRORS:
            pass

    async def _set_state(
        self, iid: int, state: str, message: str, **extra
    ) -> None:
        fields = {"state": state, "state_message": message, **extra}
        try:
            await self.client.update("model-instances", iid, fields)
        except APIError as e:
            # 404: the row was rescued/deleted under us → drop the
            # engine; 409: we lost a race with the controllers (e.g.
            # RUNNING landing after UNREACHABLE) — the transition guard
            # rejected it and reconcile re-drives legally
            if e.status == 404:
                self.engines.discard(iid)
            logger.debug(
                "%s: state write %s -> instance %d rejected: %s",
                self.name, state, iid, e,
            )
        except CLIENT_ERRORS as e:
            logger.debug(
                "%s: state write %s -> instance %d failed: %s",
                self.name, state, iid, e,
            )

    async def reconcile(self) -> None:
        """Converge local stub engines with the server's view — the
        same decision table (and the same serialization) as
        serve_manager.reconcile."""
        async with self._reconcile_lock:
            await self._reconcile_locked()

    async def _reconcile_locked(self) -> None:
        try:
            items = await self.client.list_all("model-instances")
        except CLIENT_ERRORS:
            return
        mine = set()
        for item in items:
            if item.get("worker_id") != self.worker_id:
                continue
            iid, st = item["id"], item["state"]
            mine.add(iid)
            if st == "scheduled":
                self._spawn(iid)
            elif st in ("starting", "downloading") and (
                iid not in self._starting
            ):
                # DB says mid-start but no local attempt: re-drive
                await self._set_state(
                    iid, "scheduled", "stub agent lost the start"
                )
                self._spawn(iid)
            elif st == "running" and iid not in self.engines:
                await self._set_state(
                    iid, "scheduled", "engine process lost; restarting"
                )
                self._spawn(iid)
            elif st == "unreachable":
                if iid in self.engines:
                    # engine survived the partition: resume in place
                    await self._set_state(
                        iid, "running", "engine survived worker partition"
                    )
                elif iid not in self._starting:
                    await self._set_state(
                        iid, "scheduled", "worker back; re-driving"
                    )
                    self._spawn(iid)
            elif st == "draining":
                await self._retire(iid)
            elif st == "error" and (
                iid not in self._starting and iid not in self.engines
            ):
                await self._set_state(
                    iid, "scheduled", "restart after error"
                )
                self._spawn(iid)
        for iid in list(self.engines):
            if iid not in mine:
                self.engines.discard(iid)


# ---------------------------------------------------------------------------
# Transition-legality observer
# ---------------------------------------------------------------------------


class TransitionObserver:
    """Judge EVERY instance state write against the declared lifecycle.

    Installed as a synchronous bus tap (``EventBus.add_tap``), not a
    subscriber: subscriber queues coalesce consecutive UPDATED events
    into multi-hop change pairs, which would make single-step legality
    unjudgeable. The tap sees each write exactly once, in publish
    order. Re-attached to the fresh bus after a server restart."""

    def __init__(self) -> None:
        self.violations: List[inv.Violation] = []
        self.observed: List[Tuple[int, str, str]] = []

    def attach(self, bus) -> None:
        bus.add_tap(self._tap)

    def _tap(self, event) -> None:
        if event.kind != "model_instance":
            return
        if getattr(event, "remote", False):
            # a peer's write republished by the HA change-log tail:
            # judged once already, on its ORIGIN server's bus (and a
            # coalesced replicated diff could span multiple hops,
            # which single-step legality cannot judge)
            return
        if event.type != EventType.UPDATED or not event.changes:
            return
        pair = event.changes.get("state")
        if not pair:
            return
        old, new = pair[0], pair[1]
        self.observed.append((event.id, old, new))
        v = inv.transition_violation(
            old, new, label=f"instance {event.id}"
        )
        if v is not None:
            self.violations.append(v)


# ---------------------------------------------------------------------------
# Harness
# ---------------------------------------------------------------------------


class ChaosHarness:
    """One in-process cluster: N real servers (>=2 = HA over one shared
    DB), N stub workers, seeded faults, continuous invariant checking.

    Multi-server mode boots every server IN-PROCESS against the same
    sqlite file with a shrunken lease TTL, taps every election event
    (``coordinator.election_tap_hook``) and every fenced-write attempt
    (``fencing.audit_hook``) losslessly, and swaps the coordinator's
    fatal hook so a lost lease aborts that one server instead of
    ``os._exit``-ing the whole test."""

    def __init__(
        self,
        data_dir: str,
        *,
        workers: int = 2,
        chips: int = 8,
        replicas: int = 2,
        servers: int = 1,
        ha_ttl: float = 1.0,
        heartbeat_interval: float = 0.25,
        rescue_grace: float = 1.2,
        stuck_bound: float = 15.0,
        start_delay: float = 0.08,
        extra_cfg: Optional[Dict] = None,
        stub_http: bool = True,
        stub_boot_concurrency: int = 1,
    ):
        self.data_dir = str(data_dir)
        # extra Config fields merged over the harness defaults (e.g.
        # the SLO e2e compresses burn windows and evaluator cadence)
        self.extra_cfg = dict(extra_cfg or {})
        self.n_workers = workers
        self.n_servers = max(1, servers)
        self.ha_ttl = ha_ttl
        self.chips = chips
        self.replicas = replicas
        self.heartbeat_interval = heartbeat_interval
        self.stale_after = heartbeat_interval * 4.5
        self.rescue_grace = rescue_grace
        self.stuck_bound = stuck_bound
        self.start_delay = start_delay
        self.stub_http = stub_http
        self.stub_boot_concurrency = max(1, stub_boot_concurrency)
        # live acquire-storm contenders: (coordinator, database) pairs
        # torn down at stop() if a schedule ends mid-storm
        self._storm: List[Tuple] = []

        self.servers: List = []
        self.cfgs: List[Config] = []
        self.dead: set = set()
        self.cfg: Optional[Config] = None
        self.admin: Optional[ClientSet] = None
        self._admin_token = ""
        self.observer: Optional[TransitionObserver] = None
        self.stubs: List[StubWorker] = []
        self.injector = FaultInjector()
        self.monitor_violations: List[inv.Violation] = []
        self.skipped_ops: List[ChaosOp] = []
        self.probe_results: List = []
        # kv_handoff_abort outcomes: one entry per executed op
        self.handoff_results: List[Dict] = []
        # directory_stale outcomes: one entry per executed op
        self.directory_results: List[Dict] = []
        # tenant_flood outcomes: one entry per executed op (statuses,
        # headers, polite-probe latencies — the tier-1 e2e judges
        # isolation and headers from these; fairness is judged in
        # violations() over the admitted counts)
        self.flood_results: List[Dict] = []
        # tenant name -> {"key": full api key, "tenant": "key:<id>",
        # "weight": int, "priority": int}
        self.tenants: Dict[str, Dict] = {}
        self._deployed_model = "chaos-model"
        self.election_events: List[Dict] = []
        self.fenced_audit: List[Dict] = []
        self._restores: List[asyncio.Task] = []
        self._monitor_task: Optional[asyncio.Task] = None
        self._saved_hooks: Optional[Tuple] = None

    # ---- topology ----------------------------------------------------

    @property
    def server(self):
        """First ALIVE server (back-compat accessor: single-server
        callers keep reading ``harness.server.app`` etc.)."""
        for i, srv in enumerate(self.servers):
            if i not in self.dead and srv is not None:
                return srv
        return None

    @property
    def base(self) -> str:
        srv = self.server
        if srv is None:
            return ""
        return f"http://127.0.0.1:{srv.cfg.port}"

    def alive_indexes(self) -> List[int]:
        return [
            i for i, srv in enumerate(self.servers)
            if i not in self.dead and srv is not None
        ]

    def leader_index(self) -> Optional[int]:
        for i in self.alive_indexes():
            coord = getattr(self.servers[i], "coordinator", None)
            if coord is not None and coord.is_leader:
                return i
        return None

    # ---- lifecycle ---------------------------------------------------

    async def start(self) -> None:
        from gpustack_tpu.orm import fencing
        from gpustack_tpu.server import coordinator as coordinator_mod
        from gpustack_tpu.server.server import Server

        cfg_fields = dict(
            host="127.0.0.1",
            data_dir=self.data_dir,
            disable_worker=True,
            bootstrap_password="chaos-pass",
            registration_token="chaos-tok",
            heartbeat_interval=self.heartbeat_interval,
            unreachable_rescue_after=self.rescue_grace,
            worker_connect_timeout=0.5,
            worker_control_timeout=1.5,
            worker_control_retries=2,
            shutdown_timeout=0.3,
            force_platform="cpu",
        )
        if self.n_servers > 1:
            # shared data_dir ⇒ shared state.db + shared jwt secret;
            # shrunken lease TTL keeps failover inside test budgets
            cfg_fields.update(ha=True, ha_ttl=self.ha_ttl)
        cfg_fields.update(self.extra_cfg)

        # hooks BEFORE the first boot: the very first election and the
        # very first fenced write must be observed (lossless contract)
        self._saved_hooks = (
            coordinator_mod.election_tap_hook,
            coordinator_mod.default_fatal_hook,
            fencing.audit_hook,
        )
        coordinator_mod.election_tap_hook = self._on_election
        coordinator_mod.default_fatal_hook = self._on_fatal
        fencing.audit_hook = self._on_fence_audit

        self.observer = TransitionObserver()
        for _ in range(self.n_servers):
            cfg = Config(
                **dict(cfg_fields, port=_free_port())
            ).finalize()
            server = Server(cfg)
            await server.start()
            self.cfgs.append(cfg)
            self.servers.append(server)
            self.observer.attach(server.bus)
        self.cfg = self.cfgs[0]

        self._admin_token = await self._login()
        self.admin = ClientSet(self.base, self._admin_token)

        self.stubs = [
            StubWorker(
                self.base, "chaos-tok", f"chaos-w{i}",
                chips=self.chips,
                heartbeat_interval=self.heartbeat_interval,
                start_delay=self.start_delay,
                serve_http=self.stub_http,
            )
            for i in range(self.n_workers)
        ]
        if self.stub_boot_concurrency <= 1:
            for stub in self.stubs:
                await stub.start()
        else:
            # fleet-width boots (the 1000-worker suite) register in
            # bounded parallel — sequential registration would make
            # harness boot time the thing under test
            sem = asyncio.Semaphore(self.stub_boot_concurrency)

            async def boot(stub: StubWorker) -> None:
                async with sem:
                    await stub.start()

            await asyncio.gather(*(boot(s) for s in self.stubs))
        await self._wait_workers_ready()
        self._monitor_task = asyncio.create_task(
            self._monitor(), name="chaos-monitor"
        )

    async def stop(self) -> None:
        worker_request.rpc_fault_hook = None
        if self._saved_hooks is not None:
            from gpustack_tpu.orm import fencing
            from gpustack_tpu.server import coordinator as coordinator_mod

            (
                coordinator_mod.election_tap_hook,
                coordinator_mod.default_fatal_hook,
                fencing.audit_hook,
            ) = self._saved_hooks
            self._saved_hooks = None
        if self._monitor_task:
            self._monitor_task.cancel()
        for pair in list(self._storm):
            await self._stop_contender(pair)
        for t in self._restores:
            t.cancel()
        for stub in self.stubs:
            if stub.alive:
                await stub.kill()
        if self.admin:
            await self.admin.close()
        for i, srv in enumerate(self.servers):
            if srv is not None and i not in self.dead:
                await srv.stop()

    # ---- election / fencing taps -------------------------------------

    def _on_election(self, payload: Dict) -> None:
        self.election_events.append(payload)

    def _on_fence_audit(
        self, kind: str, rid: int, epoch: int, lease: int, landed: bool
    ) -> None:
        # called from a DB writer thread: append only (GIL-atomic)
        self.fenced_audit.append({
            "ts": time.time(),
            "kind": kind, "id": rid,
            "epoch": epoch, "lease_epoch": lease, "landed": landed,
        })

    def _on_fatal(self, coordinator) -> None:
        """A leader lost its lease: in production the process dies
        (os._exit); here that one server is aborted — hard, without
        releasing the lease it no longer owns."""
        for i, srv in enumerate(self.servers):
            if srv is not None and getattr(
                srv, "coordinator", None
            ) is coordinator:
                self._restores.append(asyncio.create_task(
                    self._abort_server(i), name="chaos-fatal-abort"
                ))
                return

    async def _abort_server(self, idx: int) -> None:
        if idx in self.dead or self.servers[idx] is None:
            return
        self.dead.add(idx)
        logger.info("chaos: server %d aborted (of %d)", idx,
                    len(self.servers))
        await self.servers[idx].abort()
        await self._rebase_clients()

    async def _rebase_clients(self) -> None:
        """Re-point the admin client and every stub at a surviving
        server — the role a front-of-plane load balancer plays in a
        real HA deployment."""
        base = self.base
        if not base:
            return
        old, self.admin = self.admin, ClientSet(
            base, self._admin_token
        )
        if old is not None:
            await old.close()
        for stub in self.stubs:
            if stub.alive:
                await stub.rebase(base)

    async def _login(self) -> str:
        deadline = asyncio.get_running_loop().time() + 30.0
        async with aiohttp.ClientSession() as http:
            while True:
                try:
                    async with http.post(
                        self.base + "/auth/login",
                        json={
                            "username": "admin",
                            "password": "chaos-pass",
                        },
                        timeout=aiohttp.ClientTimeout(total=5),
                    ) as r:
                        if r.status == 200:
                            return (await r.json())["token"]
                except CLIENT_ERRORS:
                    pass
                if asyncio.get_running_loop().time() > deadline:
                    raise AssertionError("server never came up")
                await asyncio.sleep(0.2)

    async def _wait_workers_ready(self, timeout: float = 20.0) -> None:
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            workers = await self.admin.list_all("workers")
            ready = [w for w in workers if w["state"] == "ready"]
            if len(ready) >= self.n_workers:
                return
            if asyncio.get_running_loop().time() > deadline:
                raise AssertionError(
                    f"only {len(ready)}/{self.n_workers} workers ready"
                )
            await asyncio.sleep(0.1)

    # ---- workload ----------------------------------------------------

    async def deploy(
        self,
        name: str = "chaos-model",
        replicas: Optional[int] = None,
        *,
        prefill_replicas: int = 0,
        decode_replicas: int = 0,
        host_kv_cache_mb: int = 0,
    ) -> dict:
        spec = {
            "name": name,
            "preset": "tiny",
            "replicas": (
                self.replicas if replicas is None else replicas
            ),
            "max_seq_len": 256,
            "max_slots": 2,
            "distributable": False,
        }
        if host_kv_cache_mb:
            # KV-cache-backed deployment (kv-directory class): the
            # proxy's affinity/directory routing only engages when the
            # engines carry a radix host cache
            spec.update(host_kv_cache_mb=host_kv_cache_mb)
        if prefill_replicas and decode_replicas:
            # disaggregated deployment (kv-handoff class): role-tagged
            # replicas + a host KV cache so the proxy's handoff path
            # engages
            spec.update(
                prefill_replicas=prefill_replicas,
                decode_replicas=decode_replicas,
                host_kv_cache_mb=64,
            )
        self._deployed_model = name
        return await self.admin.create("models", spec)

    # ---- fault execution ---------------------------------------------

    async def run_schedule(self, ops: Sequence[ChaosOp]) -> None:
        loop = asyncio.get_running_loop()
        worker_request.rpc_fault_hook = self.injector
        start = loop.time()
        try:
            for op in sorted(ops, key=lambda o: (o.at, o.kind)):
                delay = start + op.at - loop.time()
                if delay > 0:
                    await asyncio.sleep(delay)
                logger.info("chaos op: %s", op)
                await self._apply(op)
            await self._drain_restores()
        finally:
            worker_request.rpc_fault_hook = None

    def _pick_alive(self, ordinal: int) -> Optional[StubWorker]:
        alive = [s for s in self.stubs if s.alive]
        if not alive:
            return None
        return alive[ordinal % len(alive)]

    def _restore_later(self, delay: float, fn) -> None:
        async def go():
            await asyncio.sleep(delay)
            fn()

        self._restores.append(
            asyncio.create_task(go(), name="chaos-restore")
        )

    async def _drain_restores(self) -> None:
        pending, self._restores = self._restores, []
        for t in pending:
            try:
                await t
            except asyncio.CancelledError:
                pass

    async def _apply(self, op: ChaosOp) -> None:
        stub = self._pick_alive(op.target)
        if op.kind == "worker_kill":
            alive = [s for s in self.stubs if s.alive]
            if len(alive) <= 1:
                # never kill the last worker: convergence would be
                # impossible by construction, which tests nothing
                self.skipped_ops.append(op)
                return
            await stub.kill()
        elif op.kind == "worker_suspend":
            if stub is None:
                self.skipped_ops.append(op)
                return
            stub.suspend()
            self._restore_later(
                self.stale_after * 1.6 + op.arg, stub.resume
            )
        elif op.kind == "heartbeat_blackhole":
            if stub is None:
                self.skipped_ops.append(op)
                return
            stub.hb_blackholed = True

            def restore(s=stub):
                s.hb_blackholed = False

            self._restore_later(self.stale_after * 1.6 + op.arg, restore)
        elif op.kind == "rpc_delay":
            self.injector.delay = max(0.05, op.arg)
            self._fire_probe(stub)

            def clear_delay():
                self.injector.delay = 0.0

            self._restore_later(1.0 + op.arg, clear_delay)
        elif op.kind == "rpc_drop":
            self.injector.dropping = True
            self._fire_probe(stub)

            def clear_drop():
                self.injector.dropping = False

            self._restore_later(0.6 + op.arg, clear_drop)
        elif op.kind == "engine_crash":
            if stub is None:
                self.skipped_ops.append(op)
                return
            stub.crash_engine()
        elif op.kind == "server_restart":
            await self.restart_server()
        elif op.kind == "leader_kill":
            idx = await self._wait_leader()
            if idx is None or len(self.alive_indexes()) <= 1:
                # never kill the last server: convergence would be
                # impossible by construction
                self.skipped_ops.append(op)
                return
            await self._abort_server(idx)
        elif op.kind == "leader_hang":
            idx = await self._wait_leader()
            if idx is None or len(self.alive_indexes()) <= 1:
                self.skipped_ops.append(op)
                return
            coord = self.servers[idx].coordinator
            # the leader's election loop stalls past the TTL (the
            # event-loop-hang shape) while its controllers keep
            # believing; a follower steals the lease meanwhile and the
            # hung leader's writes get FENCED. On revival it notices
            # the lost lease and takes the (injected) fatal path.
            coord.hang_gate.clear()
            self._restore_later(
                self.ha_ttl * 1.6 + op.arg, coord.hang_gate.set
            )
        elif op.kind == "acquire_storm":
            await self._acquire_storm(op)
        elif op.kind == "rolling_server_restart":
            await self._rolling_server_restart(op)
        elif op.kind == "kv_handoff_abort":
            await self._kv_handoff_abort(op)
        elif op.kind == "directory_stale":
            await self._directory_stale(op)
        elif op.kind == "tenant_flood":
            await self._tenant_flood(op)
        elif op.kind == "lease_expire":
            if len(self.alive_indexes()) <= 1:
                self.skipped_ops.append(op)
                return
            srv = self.server
            if srv is None:
                self.skipped_ops.append(op)
                return
            # force-expire AND blank the holder: the sitting leader's
            # next renewal matches nothing → deterministic fatal; any
            # peer (or a fresh election by a survivor) re-acquires
            # with a bumped epoch
            rows = await srv.db.execute(
                "SELECT holder, epoch FROM leadership WHERE id = 1"
            )
            await srv.db.execute(
                "UPDATE leadership SET expires_at = 0, holder = '' "
                "WHERE id = 1"
            )
            if rows and rows[0]["holder"]:
                # the election tap can't see an EXTERNAL revocation —
                # record it, or the victim's tap interval would run to
                # its last granted expiry and read as a false overlap
                # with its successor
                self.election_events.append({
                    "ts": time.time(),
                    "identity": rows[0]["holder"],
                    "event": "revoked",
                    "epoch": int(rows[0]["epoch"] or 0),
                    "expires_at": 0.0,
                    "ttl": self.ha_ttl,
                })
        else:
            raise ValueError(f"unknown chaos op kind {op.kind!r}")

    async def _acquire_storm(self, op: ChaosOp) -> None:
        """STORM_CONTENDERS ephemeral lease contenders (each on its own
        Database handle against the shared file) hammer the leadership
        row for ~2 TTLs. While a real leader renews they only exercise
        the contention path; any lapsed lease (a restart window, a
        prior kill) they may legitimately steal — and release
        gracefully when the storm ends, so a real server re-acquires
        within one poll. The lossless election tap judges every
        acquisition: exactly one winner per epoch, zero overlapping
        leases, no leaderless gap > 3×TTL."""
        from gpustack_tpu.orm.db import Database
        from gpustack_tpu.server.coordinator import LeaseCoordinator

        srv = self.server
        if srv is None or self.n_servers < 2:
            self.skipped_ops.append(op)
            return
        path = srv.cfg.database_path
        storm: List[Tuple] = []
        stamp = f"{op.at:.3f}".replace(".", "_")
        for i in range(STORM_CONTENDERS):
            db = Database(path)
            coord = LeaseCoordinator(
                db,
                identity=f"storm-{stamp}-{i}",
                ttl=self.ha_ttl,
                # a deposed contender just stops contending — it owns
                # no leader tasks to split-brain
                fatal_hook=lambda _c: None,
            )
            storm.append((coord, db))
        self._storm.extend(storm)
        try:
            for coord, _db in storm:
                await coord.start()
            await asyncio.sleep(self.ha_ttl * 2 + op.arg)
        finally:
            for pair in storm:
                await self._stop_contender(pair)

    async def _stop_contender(self, pair) -> None:
        coord, db = pair
        if pair in self._storm:
            self._storm.remove(pair)
        try:
            # graceful stop EXPIRES a held lease in place: a real
            # server acquires on its next tick, epoch monotonic
            await coord.stop()
        except Exception:
            logger.exception("storm contender stop failed")
        db.close()

    async def _rolling_server_restart(self, op: ChaosOp) -> None:
        """Gracefully restart every alive server one-by-one under live
        stub traffic — the production rolling deploy. A restarting
        leader hands its lease over (expire-in-place), the follower
        acquires, the restarted server rejoins as follower, and
        replication (transactional change log) resumes with zero lost
        events."""
        if len(self.alive_indexes()) < 2:
            self.skipped_ops.append(op)
            return
        for idx in list(self.alive_indexes()):
            await self.restart_server(idx)
            # let the rejoined server settle (elections + tailing)
            # before the next one goes down — a rolling deploy waits
            # for health, it does not raze the fleet at once
            await asyncio.sleep(self.ha_ttl * 0.7 + op.arg)
        await self._rebase_clients()

    async def _kv_handoff_abort(self, op: ChaosOp) -> None:
        """Kill the prefill replica's worker MID-HANDOFF: a real
        proxied chat request routes through the server's disaggregated
        path (affinity miss → X-GPUStack-KV-Source at the prefill
        replica → decode stub pulls its paced /kv/export), and the
        prefill host dies while the stream is open. The request must
        still complete (cold) and the cluster must re-converge."""
        insts = await self.admin.list_all("model-instances")
        pre = [
            i for i in insts
            if i.get("role") == "prefill" and i["state"] == "running"
        ]
        alive = [s for s in self.stubs if s.alive]
        stub = None
        if pre:
            stub = next(
                (
                    s for s in alive
                    if s.worker_id == pre[0].get("worker_id")
                ),
                None,
            )
        if stub is None or len(alive) <= 1:
            # no running prefill replica to kill, or killing it would
            # strand the cluster: nothing this op can prove
            self.skipped_ops.append(op)
            return
        # pace the export so the kill provably lands mid-stream
        stub.export_delay = max(0.2, op.arg)
        stub.export_started.clear()
        headers = {"Authorization": f"Bearer {self._admin_token}"}
        payload = {
            "model": self._deployed_model,
            "messages": [{
                "role": "user",
                "content": f"chaos handoff probe at {op.at}",
            }],
            "max_tokens": 4,
        }

        async def fire():
            async with aiohttp.ClientSession() as http:
                async with http.post(
                    self.base + "/v1/chat/completions",
                    json=payload, headers=headers,
                    timeout=aiohttp.ClientTimeout(total=30),
                ) as r:
                    return r.status, await r.json()

        task = asyncio.create_task(fire(), name="chaos-handoff-req")
        started = True
        try:
            await asyncio.wait_for(stub.export_started.wait(), 10.0)
        except asyncio.TimeoutError:
            started = False
        await stub.kill()   # the prefill host dies mid-stream
        try:
            status, body = await task
        except CLIENT_ERRORS as e:
            status, body = 0, {"error": repr(e)}
        outcomes = [
            o for s in self.stubs for o in s.handoff_outcomes
        ]
        self.handoff_results.append({
            "status": status,
            "killed_mid_stream": started,
            "decode_outcomes": outcomes,
            "content": (
                (body.get("choices") or [{}])[0]
                .get("message", {}).get("content", "")
                if isinstance(body, dict) else ""
            ),
        })

    async def _directory_stale(self, op: ChaosOp) -> None:
        """Poison the fleet KV directory with an entry naming a
        replica id that does not exist (the scrape raced an instance
        teardown — the exact window invalidate-on-exit can lose to),
        then fire a real proxied chat request whose conversation chain
        matches the poisoned key. Degradation contract: the stale
        route is COUNTED, the request completes cold on a live
        replica, and it never stalls past the handoff-timeout bound
        dialing the dead holder."""
        from gpustack_tpu.server.resilience import conversation_chain

        srv = self.server
        if srv is None:
            self.skipped_ops.append(op)
            return
        reg = srv.app["resilience"]
        models = await self.admin.list_all("models")
        model = next(
            (
                m for m in models
                if m["name"] == self._deployed_model
            ),
            None,
        )
        if model is None or not model.get("host_kv_cache_mb"):
            # directory routing never engages without a radix host
            # cache on the deployment: nothing this op can prove
            self.skipped_ops.append(op)
            return
        insts = await self.admin.list_all("model-instances")
        ghost = (
            max((i["id"] for i in insts), default=0)
            + 1000 + op.target
        )
        messages = [{
            "role": "user",
            "content": f"chaos directory probe {op.at}-{op.target}",
        }]
        chain = conversation_chain(self._deployed_model, messages)
        reg.kv_directory.update(ghost, model["id"], {
            "keys": {h: {"blocks": 8, "tail": ""} for h in chain},
            "conversations": 1,
        })
        stale0 = reg.kv_directory.stale_routes
        headers = {"Authorization": f"Bearer {self._admin_token}"}
        payload = {
            "model": self._deployed_model,
            "messages": messages,
            "max_tokens": 4,
        }
        bound = float(
            getattr(self.cfg, "kv_handoff_timeout", 10.0) or 10.0
        )
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        try:
            async with aiohttp.ClientSession() as http:
                async with http.post(
                    self.base + "/v1/chat/completions",
                    json=payload, headers=headers,
                    timeout=aiohttp.ClientTimeout(
                        total=max(30.0, bound * 3)
                    ),
                ) as r:
                    status, body = r.status, await r.json()
        except CLIENT_ERRORS as e:
            status, body = 0, {"error": repr(e)}
        elapsed = loop.time() - t0
        self.directory_results.append({
            "status": status,
            "elapsed_s": round(elapsed, 4),
            "bound_s": bound,
            "stale_counted": (
                reg.kv_directory.stale_routes > stale0
            ),
            "ghost_instance": ghost,
            "content": (
                (body.get("choices") or [{}])[0]
                .get("message", {}).get("content", "")
                if isinstance(body, dict) else ""
            ),
        })

    # ---- tenant QoS flood (noisy-neighbor class) ---------------------

    async def ensure_tenants(self) -> None:
        """Create the synthetic QoS tenants (TENANT_SPECS) as real API
        keys through the admin surface — weights/priorities land via
        the same /v2/api-keys QoS fields operators use."""
        if self.tenants:
            return
        for name, qos in TENANT_SPECS:
            created = await self.admin.request(
                "POST", "/v2/api-keys",
                json_body={"name": f"chaos-{name}", **qos},
            )
            self.tenants[name] = {
                "key": created["value"],
                "tenant": f"key:{created['id']}",
                "weight": qos.get("weight", 1),
                "priority": qos.get("priority", 0),
            }

    async def tenant_probe(
        self, name: str, session=None, timeout: float = 20.0
    ) -> Tuple[int, float, Dict[str, str]]:
        """One real proxied chat request as tenant ``name``:
        (status, elapsed_seconds, response headers); status 0 = the
        request never completed (network error)."""
        info = self.tenants[name]
        headers = {"Authorization": f"Bearer {info['key']}"}
        payload = {
            "model": self._deployed_model,
            "messages": [
                {"role": "user", "content": f"qos probe {name}"}
            ],
            "max_tokens": 4,
        }
        own = session is None
        if own:
            session = aiohttp.ClientSession()
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        try:
            async with session.post(
                self.base + "/v1/chat/completions",
                json=payload, headers=headers,
                timeout=aiohttp.ClientTimeout(total=timeout),
            ) as r:
                await r.read()
                return r.status, loop.time() - t0, dict(r.headers)
        except CLIENT_ERRORS:
            return 0, loop.time() - t0, {}
        finally:
            if own:
                await session.close()

    async def _tenant_flood(
        self,
        op: ChaosOp,
        *,
        flood_seconds: float = 2.5,
        flood_concurrency: int = 9,
        service_delay: float = 0.3,
    ) -> None:
        """One tenant floods a model through the REAL proxy while a
        polite tenant keeps probing. Both flooders (weights 3:1) run
        more concurrency than the model's admission slots, the stub
        engines serve with a synthetic service time so in-flight
        pressure is real, and every outcome is recorded for the
        fairness/isolation judgments (violations() + the tier-1 e2e)."""
        await self.ensure_tenants()
        alive = [s for s in self.stubs if s.alive]
        if not alive or self.server is None:
            self.skipped_ops.append(op)
            return
        for stub in alive:
            stub.proxy_delay = service_delay
        loop = asyncio.get_running_loop()
        stop_at = loop.time() + flood_seconds + op.arg
        statuses: Dict[str, List[int]] = {
            "flood-a": [], "flood-b": [],
        }
        shed_headers: Dict[str, List[Dict[str, str]]] = {
            "flood-a": [], "flood-b": [],
        }
        polite: List[Tuple[int, float]] = []

        async def flooder(name: str) -> None:
            async with aiohttp.ClientSession() as session:
                async def worker():
                    while loop.time() < stop_at:
                        status, _elapsed, headers = (
                            await self.tenant_probe(
                                name, session=session
                            )
                        )
                        statuses[name].append(status)
                        if status == 429:
                            if len(shed_headers[name]) < 5:
                                shed_headers[name].append(headers)
                            # spin gently: a shed answer is ~ms, and a
                            # zero-delay retry loop would make the DB
                            # thread the thing under test
                            await asyncio.sleep(0.05)

                await asyncio.gather(
                    *(worker() for _ in range(flood_concurrency))
                )

        async def polite_loop() -> None:
            async with aiohttp.ClientSession() as session:
                while loop.time() < stop_at:
                    status, elapsed, _headers = await self.tenant_probe(
                        "polite", session=session
                    )
                    polite.append((status, elapsed))
                    await asyncio.sleep(0.05)

        try:
            await asyncio.gather(
                flooder("flood-a"), flooder("flood-b"), polite_loop()
            )
        finally:
            for stub in alive:
                stub.proxy_delay = 0.0
        admitted = {
            self.tenants[n]["tenant"]: sum(
                1 for s in statuses[n] if s == 200
            )
            for n in statuses
        }
        shed = {
            self.tenants[n]["tenant"]: sum(
                1 for s in statuses[n] if s == 429
            )
            for n in statuses
        }
        self.flood_results.append({
            "admitted": admitted,
            "shed": shed,
            "shed_headers": shed_headers,
            "polite": polite,
            "weights": {
                self.tenants[n]["tenant"]: self.tenants[n]["weight"]
                for n in statuses
            },
        })

    async def _wait_leader(
        self, timeout: Optional[float] = None
    ) -> Optional[int]:
        """Index of the current leader, waiting up to ~3 TTLs for an
        election to settle (an op firing mid-failover should hit the
        NEW leader, not vanish as a skip)."""
        deadline = asyncio.get_running_loop().time() + (
            timeout if timeout is not None else self.ha_ttl * 3
        )
        while True:
            idx = self.leader_index()
            if idx is not None:
                return idx
            if asyncio.get_running_loop().time() > deadline:
                return None
            await asyncio.sleep(0.05)

    def _fire_probe(self, stub: Optional[StubWorker]) -> None:
        """Drive a real control RPC through the live server app while
        the fault window is open — exercises worker_fetch's retry tier
        end to end."""
        if stub is None or self.server is None:
            return

        async def go():
            from gpustack_tpu.orm.record import Record
            from gpustack_tpu.schemas import Worker

            try:
                srv = self.server
                if srv is None:
                    return
                Record.bind_context(srv.db, srv.bus)
                worker = await Worker.get(stub.worker_id)
                if worker is None:
                    return
                resp = await worker_request.worker_fetch(
                    srv.app, worker, "GET", "/healthz",
                    control=True,
                )
                await resp.read()
                resp.release()
                self.probe_results.append((stub.name, resp.status))
            except CLIENT_ERRORS as e:
                self.probe_results.append((stub.name, repr(e)))

        self._restores.append(
            asyncio.create_task(go(), name="chaos-probe")
        )

    async def restart_server(self, idx: int = 0) -> None:
        from gpustack_tpu.server.server import Server

        if idx in self.dead or self.servers[idx] is None:
            return
        await self.servers[idx].stop()
        self.servers[idx] = Server(self.cfgs[idx])
        # the old listener may linger a beat after cleanup
        for attempt in range(5):
            try:
                await self.servers[idx].start()
                break
            except OSError:
                if attempt == 4:
                    raise
                await asyncio.sleep(0.2)
        # fresh server ⇒ fresh bus: re-attach the lossless observer
        if self.observer is not None:
            self.observer.attach(self.servers[idx].bus)

    # ---- invariants --------------------------------------------------

    async def _records(self):
        from gpustack_tpu.orm.record import Record
        from gpustack_tpu.schemas import (
            DevInstance,
            Model,
            ModelInstance,
            Rollout,
            Worker,
        )

        # read through an ALIVE server's handle: with several
        # in-process servers the process-global binding points at
        # whichever server bound last — which may be dead (closed DB)
        # after a leader kill. The context binding is task-local, so
        # re-binding here never disturbs the servers themselves.
        srv = self.server
        if srv is None or srv.db is None:
            raise RuntimeError("no alive server")
        Record.bind_context(srv.db, srv.bus)
        return (
            await Model.all(),
            await Worker.all(),
            await ModelInstance.all(),
            await DevInstance.all(),
            await Rollout.all(),
        )

    async def _monitor(self) -> None:
        """Continuously assert the always-scope invariants mid-chaos."""
        while True:
            await asyncio.sleep(0.25)
            try:
                (
                    models, workers, instances, devs, rollouts,
                ) = await self._records()
            except Exception:
                continue  # server mid-restart: DB handle swapped
            for v in inv.snapshot_violations(
                models, workers, instances, devs,
                rollouts=rollouts,
                stuck_bound=self.stuck_bound,
                include_eventual=False,
            ):
                self.monitor_violations.append(v)

    def violations(self) -> List[inv.Violation]:
        seen = set()
        out: List[inv.Violation] = []
        election: List[inv.Violation] = []
        if self.n_servers > 1:
            election = inv.check_election_history(
                list(self.election_events), self.ha_ttl,
                now=time.time(), require_leader=bool(
                    self.alive_indexes()
                ),
            ) + inv.check_fenced_writes(list(self.fenced_audit))
        fairness: List[inv.Violation] = []
        if self.flood_results:
            # fairness invariant over every executed flood: each
            # SATURATING tenant's admitted share must track its weight
            admitted: Dict[str, int] = {}
            weights: Dict[str, int] = {}
            for fr in self.flood_results:
                for tid, n in fr["admitted"].items():
                    admitted[tid] = admitted.get(tid, 0) + n
                weights.update(fr["weights"])
            fairness = inv.check_fair_shares(admitted, weights)
        for v in (
            list(self.monitor_violations)
            + (list(self.observer.violations) if self.observer else [])
            + election
            + fairness
        ):
            key = (v.rule, v.detail)
            if key not in seen:
                seen.add(key)
                out.append(v)
        return out

    async def wait_converged(
        self, timeout: float = 30.0, settle: float = 0.6
    ) -> None:
        """Block until the declared spec holds (replica counts, all
        RUNNING on READY workers, zero always-scope violations) and
        KEEPS holding for ``settle`` seconds."""
        await self._drain_restores()
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        good_since: Optional[float] = None
        last: List[inv.Violation] = []
        while True:
            try:
                (
                    models, workers, instances, devs, rollouts,
                ) = await self._records()
                last = inv.snapshot_violations(
                    models, workers, instances, devs,
                    rollouts=rollouts,
                    stuck_bound=self.stuck_bound,
                    include_eventual=True,
                )
            except Exception as e:
                last = [inv.Violation(
                    "snapshot-failed", "always", repr(e)
                )]
            if not last:
                now = loop.time()
                if good_since is None:
                    good_since = now
                elif now - good_since >= settle:
                    return
            else:
                good_since = None
            if loop.time() > deadline:
                raise AssertionError(
                    "cluster did not converge: "
                    + "; ".join(f"{v.rule}: {v.detail}" for v in last)
                )
            await asyncio.sleep(0.15)


# ---------------------------------------------------------------------------
# One-call runner + CLI
# ---------------------------------------------------------------------------


async def run_seeded(
    data_dir: str,
    seed: int,
    *,
    kinds: Sequence[str] = FAULT_KINDS,
    ops: int = 3,
    workers: int = 2,
    replicas: int = 2,
    servers: int = 1,
    ha_ttl: float = 1.0,
    converge_timeout: float = 30.0,
    lockdep=None,
    **harness_kw,
) -> dict:
    """Boot a cluster, deploy, run the seeded schedule, wait for
    convergence; returns a report dict (raises on non-convergence).

    ``lockdep`` (a ``testing.lockdep.LockDep``) is installed for the
    whole run — every lock the cluster constructs is order- and
    hold-time-tracked — and its verdict (merged with the static
    acquisition graph) lands in the report under ``"lockdep"``."""
    gap = (0.2, 0.8)
    if any(
        k in HA_FAULT_KINDS or k in SCALE_FAULT_KINDS for k in kinds
    ):
        # leader faults / storms / rolling restarts each need an
        # election (~TTL) to play out; the gap scales with the lease
        # so ops land on a settled leader. Still a pure function of
        # (seed, shape): ha_ttl is shape.
        gap = (ha_ttl * 1.5, ha_ttl * 3.0)
    if any(k in TENANT_FAULT_KINDS for k in kinds):
        # noisy-neighbor saturation must be reachable: shrink the
        # per-model admission pool + engage the fair layer (defaults
        # kept when the caller overrides)
        extra = dict(TENANT_CFG)
        extra.update(harness_kw.get("extra_cfg") or {})
        harness_kw["extra_cfg"] = extra
    schedule = generate_schedule(
        seed, kinds=kinds, ops=ops, workers=workers, gap=gap
    )
    if lockdep is not None:
        # install BEFORE the harness exists so the servers', workers'
        # and engines' locks are all constructed tracked
        lockdep.install()
    harness = ChaosHarness(
        data_dir, workers=workers, replicas=replicas,
        servers=servers, ha_ttl=ha_ttl, **harness_kw
    )
    await harness.start()
    try:
        if any(k in DISAGG_FAULT_KINDS for k in kinds):
            # KV-handoff faults need a role-tagged deployment
            await harness.deploy(
                prefill_replicas=1, decode_replicas=1
            )
        elif any(k in KV_DIRECTORY_FAULT_KINDS for k in kinds):
            # directory faults need a KV-cache-backed deployment so
            # cached-prefix-mass routing engages
            await harness.deploy(host_kv_cache_mb=64)
        else:
            await harness.deploy()
        await harness.wait_converged(timeout=converge_timeout)
        await harness.run_schedule(schedule)
        await harness.wait_converged(timeout=converge_timeout)
        violations = harness.violations()
        report = {
            "seed": seed,
            "schedule": [dataclasses.asdict(o) for o in schedule],
            "skipped_ops": [
                dataclasses.asdict(o) for o in harness.skipped_ops
            ],
            "violations": [v.to_dict() for v in violations],
            "observed_transitions": len(harness.observer.observed),
            "probes": list(harness.probe_results),
            "rpc_faults": {
                "delayed": harness.injector.delayed,
                "dropped": harness.injector.dropped,
            },
            "servers": servers,
            "handoffs": list(harness.handoff_results),
            "directory_probes": list(harness.directory_results),
            "floods": [
                {
                    "admitted": fr["admitted"],
                    "shed": fr["shed"],
                    "polite_ok": sum(
                        1 for s, _ in fr["polite"] if s == 200
                    ),
                    "polite_total": len(fr["polite"]),
                }
                for fr in harness.flood_results
            ],
            "dead_servers": sorted(harness.dead),
            "election_events": len(harness.election_events),
            # true fence REJECTIONS only: a fenced-context write can
            # also fail to land on a plain CAS conflict or missing row
            # (lease_epoch <= epoch) — those are not fencing events
            "fenced_writes": sum(
                1 for w in harness.fenced_audit
                if not w["landed"] and w["lease_epoch"] > w["epoch"]
            ),
        }
        if lockdep is not None:
            from gpustack_tpu.testing.lockdep import (
                static_acquisition_edges,
            )

            report["lockdep"] = lockdep.report(
                static_acquisition_edges()
            )
        return report
    finally:
        await harness.stop()
        if lockdep is not None:
            lockdep.uninstall()


def main(argv=None) -> int:
    import argparse
    import json as jsonlib
    import tempfile

    p = argparse.ArgumentParser("gpustack-tpu chaos harness")
    p.add_argument(
        "--classes", default="all",
        help="comma-separated fault classes "
             f"({', '.join(FAULT_CLASSES)}; 'all' = every named class)",
    )
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--ops", type=int, default=3)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument(
        "--servers", type=int, default=0,
        help="control-plane servers (0 = auto: 2 for HA classes, "
             "1 otherwise)",
    )
    p.add_argument("--ha-ttl", type=float, default=1.0)
    p.add_argument("--timeout", type=float, default=40.0)
    p.add_argument("--verbose", action="store_true")
    p.add_argument(
        "--lockdep", action="store_true",
        help="run under the runtime lockdep monitor "
             "(testing/lockdep.py): every lock constructed by the "
             "cluster is order- and hold-time-tracked; a cycle in the "
             "merged static+observed graph or an over-threshold hold "
             "fails the class",
    )
    p.add_argument(
        "--lockdep-max-hold", type=float, default=1.0,
        help="seconds a lock may be held before lockdep flags it",
    )
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING
    )
    if args.classes == "all":
        classes = [c for c in FAULT_CLASSES if c != "mixed"]
    else:
        classes = [c.strip() for c in args.classes.split(",") if c.strip()]
    unknown = [c for c in classes if c not in FAULT_CLASSES]
    if unknown:
        print(f"unknown fault classes: {unknown}")
        return 2

    failures = 0
    for i, cls_name in enumerate(classes):
        seed = args.seed + i
        tmp = tempfile.mkdtemp(prefix=f"chaos-{cls_name}-")
        servers = args.servers or (
            2 if cls_name in MULTI_SERVER_CLASSES else 1
        )
        print(f"=== {cls_name} (seed {seed}, servers {servers}) ===")
        monitor = None
        if args.lockdep:
            from gpustack_tpu.testing.lockdep import LockDep

            monitor = LockDep(max_hold_s=args.lockdep_max_hold)
        try:
            report = asyncio.run(run_seeded(
                tmp, seed,
                kinds=FAULT_CLASSES[cls_name],
                ops=args.ops,
                workers=args.workers,
                replicas=args.replicas,
                servers=servers,
                ha_ttl=args.ha_ttl,
                converge_timeout=args.timeout,
                lockdep=monitor,
            ))
        except Exception as e:  # noqa: BLE001 — CLI boundary
            print(f"FAIL {cls_name}: {e}")
            failures += 1
            continue
        lock_findings = (
            report.get("lockdep", {}).get("findings", [])
        )
        if report["violations"]:
            print(f"FAIL {cls_name}: invariant violations")
            print(jsonlib.dumps(report["violations"], indent=2))
            failures += 1
        elif lock_findings:
            print(f"FAIL {cls_name}: lockdep findings")
            print(jsonlib.dumps(lock_findings, indent=2))
            failures += 1
        else:
            print(
                f"PASS {cls_name}: converged; "
                f"{report['observed_transitions']} transitions observed, "
                f"schedule {report['schedule']}"
            )
            if monitor is not None:
                ld = report.get("lockdep", {})
                print(
                    f"    lockdep: {ld.get('locks_tracked', 0)} locks, "
                    f"{ld.get('observed_edges', 0)} observed + "
                    f"{ld.get('static_edges', 0)} static edges, "
                    f"0 findings"
                )
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
