"""Control-plane convergence invariants.

The properties the cluster manager promises to hold — checked by the
chaos harness (testing/chaos.py) mid-fault and at quiescence, and
exposed for production triage at ``GET /v2/debug/invariants``
(routes/extras.py).

Two scopes:

- ``always``: must hold at every instant, even mid-chaos. A violation
  is a bug no matter when it is observed:
    * no chip is claimed by two live placements on the same worker;
    * chip accounting is conserved (claims reference real, usable chips
      on a known worker);
    * no instance sits in a transient state longer than the bound
      (something must always be driving it forward);
    * every observed state write follows ``INSTANCE_STATE_TRANSITIONS``
      (checked by the event observer, not the snapshot).
- ``eventual``: may be transiently false while controllers converge
  (a worker just died; its instances are still marked RUNNING for a
  beat) but must hold at quiescence:
    * every RUNNING instance's worker is READY;
    * every model's replica count matches its spec, all RUNNING.

All check functions are pure (records in, violations out) so they run
identically inside the harness, inside the debug endpoint, and in unit
tests.
"""

from __future__ import annotations

import dataclasses
import datetime
from typing import Dict, Iterable, List, Optional, Sequence

from gpustack_tpu.policies.allocatable import (
    CLAIMING_STATES,
    DEV_CLAIMING_STATES,
)
from gpustack_tpu.schemas import (
    ModelInstanceState,
    WorkerState,
    validate_instance_transition,
)

# states an instance may only pass through, never rest in — something
# (scheduler, worker agent, controller) must always be driving it on
TRANSIENT_STATES = {
    ModelInstanceState.ANALYZING,
    ModelInstanceState.SCHEDULED,
    ModelInstanceState.DOWNLOADING,
    ModelInstanceState.STARTING,
    ModelInstanceState.DRAINING,
}

DEFAULT_STUCK_BOUND = 600.0


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str      # short machine id, e.g. "double-chip-claim"
    scope: str     # "always" | "eventual"
    detail: str

    def to_dict(self) -> Dict[str, str]:
        return dataclasses.asdict(self)


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def _claims_by_worker(instances: Iterable, dev_instances: Iterable):
    """worker_id -> list of (owner-label, chip_index) for every live
    claim, including multi-host subordinate legs."""
    out: Dict[int, List] = {}

    def add(worker_id, label, chips):
        out.setdefault(int(worker_id), []).extend(
            (label, int(c)) for c in chips
        )

    for inst in instances:
        if inst.state not in CLAIMING_STATES:
            continue
        if inst.worker_id:
            add(inst.worker_id, f"instance {inst.name}", inst.chip_indexes)
        for sub in inst.subordinate_workers:
            if sub.worker_id:
                add(
                    sub.worker_id,
                    f"instance {inst.name} (subordinate)",
                    sub.chip_indexes,
                )
    for dev in dev_instances:
        if getattr(dev, "state", None) in DEV_CLAIMING_STATES and (
            dev.worker_id
        ):
            add(dev.worker_id, f"dev {dev.name}", dev.chip_indexes)
    return out


def check_chip_claims(
    workers: Sequence,
    instances: Sequence,
    dev_instances: Sequence = (),
) -> List[Violation]:
    """No double claim; every claim lands on a real usable chip of a
    known worker (conservation)."""
    out: List[Violation] = []
    by_id = {w.id: w for w in workers}
    for worker_id, claims in _claims_by_worker(
        instances, dev_instances
    ).items():
        worker = by_id.get(worker_id)
        seen: Dict[int, str] = {}
        for label, chip in claims:
            if chip in seen:
                out.append(Violation(
                    "double-chip-claim", "always",
                    f"worker {worker_id}: chip {chip} claimed by both "
                    f"{seen[chip]} and {label}",
                ))
            else:
                seen[chip] = label
        if worker is None:
            out.append(Violation(
                "claim-unknown-worker", "always",
                f"{len(claims)} chip claim(s) reference worker "
                f"{worker_id}, which does not exist",
            ))
            continue
        usable = {c.index for c in worker.status.chips if c.usable}
        bogus = sorted({c for _, c in claims} - usable)
        if bogus:
            out.append(Violation(
                "chip-conservation", "always",
                f"worker {worker.name or worker_id}: claimed chip(s) "
                f"{bogus} are not usable chips of this worker "
                f"(usable: {sorted(usable)})",
            ))
    return out


def check_stuck_transient(
    instances: Sequence,
    now: Optional[datetime.datetime] = None,
    bound: float = DEFAULT_STUCK_BOUND,
) -> List[Violation]:
    now = now or _now()
    out: List[Violation] = []
    for inst in instances:
        if inst.state not in TRANSIENT_STATES:
            continue
        try:
            updated = datetime.datetime.fromisoformat(inst.updated_at)
        except ValueError:
            continue
        age = (now - updated).total_seconds()
        if age > bound:
            out.append(Violation(
                "stuck-transient-state", "always",
                f"instance {inst.name} has sat in "
                f"{inst.state.value} for {age:.0f}s (> {bound:.0f}s)",
            ))
    return out


def check_running_worker_ready(
    workers: Sequence, instances: Sequence
) -> List[Violation]:
    by_id = {w.id: w for w in workers}
    out: List[Violation] = []
    for inst in instances:
        if inst.state != ModelInstanceState.RUNNING:
            continue
        worker = by_id.get(inst.worker_id or 0)
        if worker is None:
            out.append(Violation(
                "running-without-worker", "eventual",
                f"instance {inst.name} is RUNNING on worker "
                f"{inst.worker_id}, which does not exist",
            ))
        elif worker.state != WorkerState.READY:
            out.append(Violation(
                "running-on-unready-worker", "eventual",
                f"instance {inst.name} is RUNNING but its worker "
                f"{worker.name} is {worker.state.value}",
            ))
    return out


def check_replica_convergence(
    models: Sequence, instances: Sequence, rollouts: Sequence = ()
) -> List[Violation]:
    from gpustack_tpu.schemas.rollouts import ACTIVE_ROLLOUT_STATES

    mid_rollout = {
        r.model_id for r in rollouts
        if r.state in ACTIVE_ROLLOUT_STATES
    }
    per_model: Dict[int, List] = {}
    for inst in instances:
        per_model.setdefault(inst.model_id, []).append(inst)
    out: List[Violation] = []
    for model in models:
        if model.id in mid_rollout:
            # a rollout deliberately runs spec+surge replicas and
            # drains batches — its own surge-cap check governs here
            continue
        mine = per_model.get(model.id, [])
        want = model.serving_replicas()
        if len(mine) != want:
            out.append(Violation(
                "replica-count-diverged", "eventual",
                f"model {model.name}: {len(mine)} instance(s), "
                f"spec says {want}",
            ))
        # disaggregated models must also converge PER ROLE: the right
        # total with the wrong prefill/decode split still can't serve
        # (checked for colocated models only when stray role tags
        # exist, so the total check isn't double-reported)
        if model.disaggregated or any(i.role for i in mine):
            for role, want_role in model.role_spec().items():
                have_role = sum(1 for i in mine if i.role == role)
                if have_role != want_role:
                    out.append(Violation(
                        "replica-role-diverged", "eventual",
                        f"model {model.name}: {have_role} "
                        f"{role or 'untagged'} instance(s), spec says "
                        f"{want_role}",
                    ))
        not_running = [
            f"{i.name}={i.state.value}"
            for i in mine
            if i.state != ModelInstanceState.RUNNING
        ]
        if not_running:
            out.append(Violation(
                "replicas-not-running", "eventual",
                f"model {model.name}: {', '.join(not_running)}",
            ))
    return out


def check_rollout_surge(
    models: Sequence, instances: Sequence, rollouts: Sequence
) -> List[Violation]:
    """During an active rollout the controller may run at most
    ``promoted + surge`` NEW-generation instances — always-scope: it
    creates batch-by-batch, so exceeding that at any instant is a
    runaway surge loop, not mid-convergence noise. The bound is on the
    new generation (the only thing the controller creates), NOT on the
    total against the current spec: an operator shrinking ``replicas``
    mid-rollout legitimately leaves the total above ``replicas +
    surge`` until the excess old batch drains."""
    from gpustack_tpu.schemas.rollouts import ACTIVE_ROLLOUT_STATES

    models_by_id = {m.id: m for m in models}
    out: List[Violation] = []
    for r in rollouts:
        if r.state not in ACTIVE_ROLLOUT_STATES:
            continue
        model = models_by_id.get(r.model_id)
        if model is None:
            continue
        cap = r.promoted + max(1, r.surge)
        have = sum(
            1 for inst in instances
            if inst.model_id == r.model_id
            and inst.generation == r.to_generation
        )
        if have > cap:
            out.append(Violation(
                "rollout-surge-exceeded", "always",
                f"model {model.name}: {have} new-generation "
                f"instance(s) during rollout {r.id}, surge cap is "
                f"{cap} (promoted {r.promoted} + surge {r.surge})",
            ))
        if model.disaggregated:
            # the surge cap applies PER ROLE for disaggregated models:
            # surge batches draw from the new generation's role
            # deficit, so any role exceeding its spec + surge is a
            # runaway creation loop in that role's population
            for role, spec_role in model.role_spec().items():
                have_role = sum(
                    1 for inst in instances
                    if inst.model_id == r.model_id
                    and inst.generation == r.to_generation
                    and inst.role == role
                )
                role_cap = spec_role + max(1, r.surge)
                if have_role > role_cap:
                    out.append(Violation(
                        "rollout-role-surge-exceeded", "always",
                        f"model {model.name}: {have_role} "
                        f"new-generation {role or 'untagged'} "
                        f"instance(s) during rollout {r.id}, per-role "
                        f"cap is {role_cap}",
                    ))
    return out


def check_generation_converged(
    models: Sequence, instances: Sequence, rollouts: Sequence
) -> List[Violation]:
    """With no rollout mid-flight every instance must serve the
    model's current generation — eventual-scope (an operator update
    legitimately mismatches for the beat before the controller opens
    a plan), but persistent mixing means a rollout stalled or leaked
    replicas across generations."""
    from gpustack_tpu.schemas.rollouts import ACTIVE_ROLLOUT_STATES

    active_models = {
        r.model_id for r in rollouts
        if r.state in ACTIVE_ROLLOUT_STATES
    }
    per_model: Dict[int, List] = {}
    for inst in instances:
        per_model.setdefault(inst.model_id, []).append(inst)
    out: List[Violation] = []
    for model in models:
        if model.id in active_models:
            continue
        mixed = [
            f"{i.name}=g{i.generation}"
            for i in per_model.get(model.id, [])
            if i.generation != model.generation
        ]
        if mixed:
            out.append(Violation(
                "generation-mixing", "eventual",
                f"model {model.name} is at generation "
                f"{model.generation} with no active rollout, but: "
                + ", ".join(mixed),
            ))
    return out


def check_autoscale_bounds(models: Sequence) -> List[Violation]:
    """Autoscaled models keep their replica spec inside
    [autoscale_min, autoscale_max] — eventual-scope: an operator may
    write an out-of-bounds count, which the autoscaler's next tick
    corrects."""
    out: List[Violation] = []
    for model in models:
        if model.autoscale_max <= 0:
            continue
        lo = max(0, model.autoscale_min)
        hi = max(lo, model.autoscale_max)
        # disaggregated models autoscale their decode role only (the
        # autoscaler additionally floors lo at 1 there — decode 0
        # would flip the model out of disaggregated mode)
        if model.disaggregated:
            lo = max(1, lo)
            scaled = model.decode_replicas
            what = "decode_replicas"
        else:
            scaled = model.replicas
            what = "replicas"
        if not lo <= scaled <= hi:
            out.append(Violation(
                "autoscale-bounds", "eventual",
                f"model {model.name}: {what} {scaled} "
                f"outside autoscale bounds [{lo}, {hi}]",
            ))
    return out


def check_election_history(
    events: Sequence[Dict],
    ttl: float,
    *,
    now: Optional[float] = None,
    require_leader: bool = False,
) -> List[Violation]:
    """Judge a lossless election-event stream (coordinator.py
    ``election_tap_hook`` payloads: ts/identity/event/epoch/
    expires_at/ttl) against the HA contract:

    - **at-most-one-leader** (always): lease-validity intervals never
      overlap. An interval opens at ``acquired``, its expiry advances
      with every ``renewed``, and it closes at ``lost``/``released``
      — or, for a leader that died silently (SIGKILL), at its last
      granted expiry. Two overlapping intervals mean two coordinators
      simultaneously held *valid* leases — the split-brain fencing
      exists to make unreachable.
    - **epoch monotonicity** (always): every acquisition's fencing
      epoch is strictly greater than all before it — exactly one
      winner per epoch, no reuse.
    - **leader-exists-within-3×TTL** (eventual): no leaderless gap
      between consecutive intervals (or after the last one, with
      ``require_leader``) exceeds 3×TTL.
    """
    out: List[Violation] = []
    intervals: List[Dict] = []  # {identity, epoch, start, end, open}
    open_by_identity: Dict[str, Dict] = {}
    last_epoch = 0
    for ev in sorted(events, key=lambda e: e["ts"]):
        kind = ev["event"]
        identity = ev["identity"]
        if kind == "acquired":
            epoch = int(ev.get("epoch", 0))
            if epoch <= last_epoch:
                out.append(Violation(
                    "epoch-regression", "always",
                    f"{identity} acquired epoch {epoch} but epoch "
                    f"{last_epoch} was already granted",
                ))
            last_epoch = max(last_epoch, epoch)
            iv = {
                "identity": identity,
                "epoch": epoch,
                "start": ev["ts"],
                "end": ev.get("expires_at") or (ev["ts"] + ttl),
                "open": True,
            }
            intervals.append(iv)
            open_by_identity[identity] = iv
        elif kind == "renewed":
            iv = open_by_identity.get(identity)
            if iv is not None:
                iv["end"] = max(
                    iv["end"], ev.get("expires_at") or ev["ts"]
                )
        elif kind in ("lost", "released", "fatal", "revoked"):
            # "revoked": an EXTERNAL actor invalidated the lease (the
            # chaos harness's lease_expire fault) — the holder's
            # validity ends at revocation time, not at the expiry it
            # was last granted
            iv = open_by_identity.pop(identity, None)
            if iv is not None:
                iv["end"] = min(iv["end"], ev["ts"])
                iv["open"] = False
    # overlap + gap checks over start-ordered intervals
    intervals.sort(key=lambda iv: iv["start"])
    for prev, cur in zip(intervals, intervals[1:]):
        if cur["start"] < prev["end"] - 1e-6:
            out.append(Violation(
                "overlapping-leases", "always",
                f"{cur['identity']} (epoch {cur['epoch']}) acquired at "
                f"{cur['start']:.3f} while {prev['identity']} (epoch "
                f"{prev['epoch']})'s lease was valid until "
                f"{prev['end']:.3f}",
            ))
        gap = cur["start"] - prev["end"]
        if gap > 3 * ttl:
            out.append(Violation(
                "leaderless-too-long", "eventual",
                f"no leader for {gap:.2f}s between "
                f"{prev['identity']} and {cur['identity']} "
                f"(bound 3*ttl = {3 * ttl:.2f}s)",
            ))
    if require_leader and intervals:
        last = max(intervals, key=lambda iv: iv["end"])
        end_now = now if now is not None else last["end"]
        if not any(
            iv["open"] and iv["end"] >= end_now - 1e-6
            for iv in intervals
        ):
            gap = end_now - last["end"]
            if gap > 3 * ttl:
                out.append(Violation(
                    "leaderless-too-long", "eventual",
                    f"no leader for the trailing {gap:.2f}s "
                    f"(bound 3*ttl = {3 * ttl:.2f}s)",
                ))
    if require_leader and not intervals:
        out.append(Violation(
            "leaderless-too-long", "eventual",
            "no acquisition was ever observed",
        ))
    return out


def check_fenced_writes(writes: Sequence[Dict]) -> List[Violation]:
    """**no-stale-epoch-write** (always), from the lossless fencing
    audit tap (orm/fencing.py ``audit_hook``): every write that LANDED
    must have carried an epoch >= the lease epoch observed inside its
    own transaction. A landed write with a smaller epoch is a deposed
    leader mutating its successor's state — the exact corruption the
    fence exists to make impossible, so one occurrence is a fencing
    bug no matter when it happens."""
    out: List[Violation] = []
    for w in writes:
        if w.get("landed") and w.get("lease_epoch", 0) > w.get(
            "epoch", 0
        ):
            out.append(Violation(
                "stale-epoch-write", "always",
                f"{w.get('kind')} id={w.get('id')}: write with epoch "
                f"{w.get('epoch')} landed while the lease epoch was "
                f"{w.get('lease_epoch')}",
            ))
    return out


def check_changelog_durability(
    committed: Sequence[Dict],
    observed: Sequence[Dict],
) -> List[Violation]:
    """**no-lost-replication-event** (always): every write COMMITTED on
    the origin server before it died must be observed by a surviving
    peer — either republished on its bus or present in the shared
    ``change_log``. With transactional appends (orm/changelog.py) this
    holds by construction even for a SIGKILL the instant after commit;
    a miss means an event made it to the data table without its
    replication entry, the exact crash window ISSUE 15 closes.

    ``committed``/``observed`` entries are ``{kind, id, type}`` dicts
    (type = CREATED/UPDATED/DELETED). Pure, so the chaos harness and
    e2es judge identical math."""
    seen = {
        (o.get("kind"), int(o.get("id", 0)), o.get("type"))
        for o in observed
    }
    out: List[Violation] = []
    for c in committed:
        key = (c.get("kind"), int(c.get("id", 0)), c.get("type"))
        if key not in seen:
            out.append(Violation(
                "lost-replication-event", "always",
                f"{key[2]} {key[0]} id={key[1]} committed on the "
                "origin but never observed by any surviving peer",
            ))
    return out


def check_fair_shares(
    admitted: Dict[str, int],
    weights: Dict[str, int],
    eps: float = 0.2,
) -> List[Violation]:
    """**tenant-fair-share** (always): among tenants that SATURATED a
    model (the chaos harness's flooders), each tenant's share of the
    admitted requests must sit within ``eps`` of its weight share —
    the convergence guarantee the tenancy layer's weighted-fair
    admission promises (server/tenancy.py). Pure so the harness, the
    e2e and unit tests judge identical math."""
    out: List[Violation] = []
    total_admitted = sum(admitted.get(t, 0) for t in weights)
    total_weight = sum(max(1, w) for w in weights.values())
    if total_admitted <= 0 or total_weight <= 0 or len(weights) < 2:
        return out
    for tenant, weight in sorted(weights.items()):
        share = admitted.get(tenant, 0) / total_admitted
        fair = max(1, weight) / total_weight
        if abs(share - fair) > eps:
            out.append(Violation(
                "tenant-fair-share", "always",
                f"tenant {tenant}: admitted share {share:.3f} vs "
                f"weight share {fair:.3f} (weight {weight}, "
                f"eps {eps})",
            ))
    return out


def transition_violation(
    old: str, new: str, label: str = ""
) -> Optional[Violation]:
    """Judge one observed state write (from a watch event's
    ``changes['state']`` pair) against the declared lifecycle."""
    try:
        old_s = ModelInstanceState(old)
        new_s = ModelInstanceState(new)
    except ValueError:
        return Violation(
            "unknown-state-written", "always",
            f"{label}: {old!r} -> {new!r}",
        )
    if validate_instance_transition(old_s, new_s):
        return None
    return Violation(
        "illegal-state-transition", "always",
        f"{label}: {old_s.value} -> {new_s.value} is not declared in "
        f"INSTANCE_STATE_TRANSITIONS",
    )


def snapshot_violations(
    models: Sequence,
    workers: Sequence,
    instances: Sequence,
    dev_instances: Sequence = (),
    *,
    rollouts: Sequence = (),
    now: Optional[datetime.datetime] = None,
    stuck_bound: float = DEFAULT_STUCK_BOUND,
    include_eventual: bool = True,
) -> List[Violation]:
    """All snapshot-checkable invariants over one consistent read.
    ``include_eventual=False`` is the mid-chaos mode: controllers are
    allowed to be mid-convergence."""
    out = check_chip_claims(workers, instances, dev_instances)
    out += check_stuck_transient(instances, now=now, bound=stuck_bound)
    out += check_rollout_surge(models, instances, rollouts)
    if include_eventual:
        out += check_running_worker_ready(workers, instances)
        out += check_replica_convergence(models, instances, rollouts)
        out += check_generation_converged(models, instances, rollouts)
        out += check_autoscale_bounds(models)
    return out


async def control_plane_snapshot(
    stuck_bound: float = DEFAULT_STUCK_BOUND,
) -> Dict:
    """Server-side report over the live records (the debug endpoint's
    body). ``always``-scope violations are bugs; ``eventual``-scope
    entries are listed separately — mid-convergence they are expected,
    persistently they point at the stuck component."""
    from gpustack_tpu.schemas import DevInstance, Model, Rollout, Worker
    from gpustack_tpu.schemas import ModelInstance as MI

    models = await Model.all()
    workers = await Worker.all()
    instances = await MI.all()
    devs = await DevInstance.all()
    rollouts = await Rollout.all()
    violations = snapshot_violations(
        models, workers, instances, devs,
        rollouts=rollouts,
        stuck_bound=stuck_bound, include_eventual=True,
    )
    return {
        "checked_at": _now().isoformat(),
        "stuck_bound_seconds": stuck_bound,
        "counts": {
            "models": len(models),
            "workers": len(workers),
            "instances": len(instances),
            "dev_instances": len(devs),
            "rollouts": len(rollouts),
        },
        "violations": [
            v.to_dict() for v in violations if v.scope == "always"
        ],
        "eventual": [
            v.to_dict() for v in violations if v.scope == "eventual"
        ],
    }
