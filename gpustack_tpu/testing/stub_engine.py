"""Minimal EXTERNAL OpenAI-compatible engine for backend orchestration.

This process stands in for the third-party engines the reference
orchestrates (vLLM / SGLang / llama-box — reference
worker/backends/base.py:150 and custom.py:24): it is launched from an
InferenceBackend catalog command template through the SAME ServeManager
path a real external binary would be, and speaks the contract that path
assumes:

- readiness endpoint at ``/health`` (deliberately NOT /healthz — proves
  the catalog's ``health_path`` is honored, like vLLM's /health),
- ``/v1/chat/completions`` + ``/v1/completions`` (stream and non-stream),
- ``/v1/models``,
- Prometheus ``/metrics`` using vLLM's metric names so the worker's
  runtime-metrics normalization (worker/metrics_map.py) has something
  real to map.

It generates deterministic text (echo-ish) with no model weights, so the
e2e can assert content flowed through the proxy without caring about
quality. Fast startup is a feature: crash-restart tests measure the
manager, not a model load.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
import uuid

from aiohttp import web

START = time.time()
STATS = {"requests": 0, "prompt_tokens": 0, "generation_tokens": 0}


def _gen_text(prompt: str, max_tokens: int) -> str:
    words = (prompt.strip() or "ok").split()
    out = []
    i = 0
    while len(out) < max(1, min(max_tokens, 64)):
        out.append(words[i % len(words)])
        i += 1
    return "stub: " + " ".join(out)


def _usage(prompt: str, text: str) -> dict:
    pt, ct = len(prompt.split()), len(text.split())
    STATS["requests"] += 1
    STATS["prompt_tokens"] += pt
    STATS["generation_tokens"] += ct
    return {
        "prompt_tokens": pt,
        "completion_tokens": ct,
        "total_tokens": pt + ct,
    }


def _bucket(n: int) -> int:
    """Power-of-two padding stand-in for the real runner's prefill
    buckets — gives the stub's flight records a nonzero padding waste
    the fleet-rollup e2e can assert on."""
    b = 1
    while b < max(1, n):
        b *= 2
    return b


def build_app(
    served_name: str,
    fail_health_after: float = 0.0,
    token_delay: float = 0.0,
) -> web.Application:
    from gpustack_tpu.observability.flight import FlightRecorder
    from gpustack_tpu.observability.tracing import trace_middleware

    # same trace hop contract as the real engine (engine/api_server.py):
    # hermetic e2es assert the full four-hop trace against this stub
    app = web.Application(middlewares=[trace_middleware("engine")])
    # same flight-recorder contract as the real engine: one prefill +
    # one decode record per generation, served at /debug/flight and on
    # /metrics, so `GET /v2/debug/fleet` consistency is e2e-testable
    # without TPUs
    flight = FlightRecorder(slots_total=4)
    app["flight"] = flight

    def record_generation(pt: int, ct: int, dur_s: float) -> None:
        flight.record(
            dur_s=dur_s / 2, mode="prefill", slots_used=1,
            waiting=0, oldest_wait_s=0.0,
            tokens_real=pt, tokens_padded=_bucket(pt),
            tokens_out=1, prompt_tokens=pt,
        )
        flight.record(
            dur_s=dur_s / 2, mode="decode", slots_used=1,
            waiting=0, oldest_wait_s=0.0,
            tokens_real=max(0, ct - 1),
            tokens_padded=flight.slots_total * max(0, ct - 1),
            tokens_out=max(0, ct - 1),
        )

    async def health(_request):
        if fail_health_after and time.time() - START > fail_health_after:
            return web.json_response({"status": "failing"}, status=503)
        return web.json_response({"status": "ok"})

    async def models(_request):
        return web.json_response({
            "object": "list",
            "data": [{"id": served_name, "object": "model",
                      "owned_by": "stub"}],
        })

    async def chat(request: web.Request):
        body = await request.json()
        prompt = " ".join(
            str(m.get("content", "")) for m in body.get("messages", [])
        )
        t0 = time.perf_counter()
        text = _gen_text(prompt, int(body.get("max_tokens", 16)))
        usage = _usage(prompt, text)
        record_generation(
            usage["prompt_tokens"], usage["completion_tokens"],
            time.perf_counter() - t0,
        )
        rid = f"chatcmpl-{uuid.uuid4().hex[:12]}"
        if body.get("stream"):
            resp = web.StreamResponse(
                headers={"Content-Type": "text/event-stream"}
            )
            await resp.prepare(request)
            for piece in text.split(" "):
                chunk = {
                    "id": rid, "object": "chat.completion.chunk",
                    "model": served_name,
                    "choices": [{"index": 0,
                                 "delta": {"content": piece + " "},
                                 "finish_reason": None}],
                }
                await resp.write(
                    f"data: {json.dumps(chunk)}\n\n".encode()
                )
                # paced streaming (drain tests need a generation that is
                # genuinely in flight while the instance drains)
                await asyncio.sleep(token_delay)
            done = {
                "id": rid, "object": "chat.completion.chunk",
                "model": served_name,
                "choices": [{"index": 0, "delta": {},
                             "finish_reason": "stop"}],
                "usage": usage,
            }
            await resp.write(f"data: {json.dumps(done)}\n\n".encode())
            await resp.write(b"data: [DONE]\n\n")
            return resp
        return web.json_response({
            "id": rid, "object": "chat.completion",
            "created": int(time.time()), "model": served_name,
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": "stop",
            }],
            "usage": usage,
        })

    async def completions(request: web.Request):
        body = await request.json()
        prompt = str(body.get("prompt", ""))
        t0 = time.perf_counter()
        text = _gen_text(prompt, int(body.get("max_tokens", 16)))
        usage = _usage(prompt, text)
        record_generation(
            usage["prompt_tokens"], usage["completion_tokens"],
            time.perf_counter() - t0,
        )
        return web.json_response({
            "id": f"cmpl-{uuid.uuid4().hex[:12]}",
            "object": "text_completion",
            "created": int(time.time()), "model": served_name,
            "choices": [{"index": 0, "text": text,
                         "finish_reason": "stop"}],
            "usage": usage,
        })

    async def metrics(_request):
        # vLLM metric names → exercised by worker/metrics_map.py
        lines = [
            "# TYPE vllm:num_requests_running gauge",
            "vllm:num_requests_running 0",
            "# TYPE vllm:prompt_tokens_total counter",
            f"vllm:prompt_tokens_total {STATS['prompt_tokens']}",
            "# TYPE vllm:generation_tokens_total counter",
            f"vllm:generation_tokens_total {STATS['generation_tokens']}",
            "# TYPE vllm:request_success_total counter",
            f"vllm:request_success_total {STATS['requests']}",
            # in-repo engine gauge names too, so the fleet rollup's
            # slots/occupancy math is exercised against the stub
            "# TYPE gpustack_engine_slots_total gauge",
            f"gpustack_engine_slots_total {flight.slots_total}",
            "# TYPE gpustack_engine_slots_used gauge",
            "gpustack_engine_slots_used 0",
            "# TYPE gpustack_engine_waiting gauge",
            "gpustack_engine_waiting 0",
        ]
        # flight families ride along exactly like the real engine
        # exporter, so the worker's normalization and the server's
        # fleet rollup see the full vocabulary in hermetic e2es
        lines.extend(flight.metrics_lines())
        return web.Response(text="\n".join(lines) + "\n")

    async def debug_flight(request: web.Request):
        try:
            limit = min(2048, int(request.query.get("limit", 100)))
        except ValueError:
            return web.json_response(
                {"error": "limit must be an integer"}, status=400
            )
        return web.json_response({
            "model": served_name,
            "records": flight.snapshot(limit=limit),
            "aggregate": flight.aggregate(),
            "overhead_ratio": round(flight.overhead_ratio(), 6),
        })

    async def debug_profile(request: web.Request):
        # the stub has no jax: permanently the flight-only degradation
        # path of the real engine's /debug/profile contract
        try:
            steps = int(request.query.get("steps", 20))
        except ValueError:
            return web.json_response(
                {"error": "steps must be an integer"}, status=400
            )
        records = flight.snapshot(limit=max(1, steps))
        from gpustack_tpu.observability.flight import aggregate_records

        return web.json_response({
            "requested": steps,
            "steps_captured": len(records),
            "profiler": "flight-only",
            "artifact": "",
            "error": "jax.profiler.start_trace unavailable",
            "records": records,
            "aggregate": aggregate_records(
                records, flight.slots_total
            ) if records else {},
        })

    app.router.add_get("/health", health)
    app.router.add_get("/v1/models", models)
    app.router.add_post("/v1/chat/completions", chat)
    app.router.add_post("/v1/completions", completions)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/debug/flight", debug_flight)
    app.router.add_post("/debug/profile", debug_profile)
    return app


def main(argv=None) -> None:
    p = argparse.ArgumentParser("stub external engine")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--served-name", default="stub-model")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument(
        "--fail-health-after", type=float, default=0.0,
        help="seconds after which /health flips 503 (crash-path tests)",
    )
    p.add_argument(
        "--token-delay", type=float, default=0.0,
        help="seconds between streamed SSE chunks (drain tests)",
    )
    args = p.parse_args(argv)
    web.run_app(
        build_app(
            args.served_name, args.fail_health_after, args.token_delay
        ),
        host=args.host, port=args.port, print=None,
    )


if __name__ == "__main__":
    main()
