"""Minimal EXTERNAL OpenAI-compatible engine for backend orchestration.

This process stands in for the third-party engines the reference
orchestrates (vLLM / SGLang / llama-box — reference
worker/backends/base.py:150 and custom.py:24): it is launched from an
InferenceBackend catalog command template through the SAME ServeManager
path a real external binary would be, and speaks the contract that path
assumes:

- readiness endpoint at ``/health`` (deliberately NOT /healthz — proves
  the catalog's ``health_path`` is honored, like vLLM's /health),
- ``/v1/chat/completions`` + ``/v1/completions`` (stream and non-stream),
- ``/v1/models``,
- Prometheus ``/metrics`` using vLLM's metric names so the worker's
  runtime-metrics normalization (worker/metrics_map.py) has something
  real to map.

It generates deterministic text (echo-ish) with no model weights, so the
e2e can assert content flowed through the proxy without caring about
quality. Fast startup is a feature: crash-restart tests measure the
manager, not a model load.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
import uuid

from aiohttp import web

START = time.time()
STATS = {"requests": 0, "prompt_tokens": 0, "generation_tokens": 0}


def _gen_text(prompt: str, max_tokens: int) -> str:
    words = (prompt.strip() or "ok").split()
    out = []
    i = 0
    while len(out) < max(1, min(max_tokens, 64)):
        out.append(words[i % len(words)])
        i += 1
    return "stub: " + " ".join(out)


def _usage(prompt: str, text: str) -> dict:
    pt, ct = len(prompt.split()), len(text.split())
    STATS["requests"] += 1
    STATS["prompt_tokens"] += pt
    STATS["generation_tokens"] += ct
    return {
        "prompt_tokens": pt,
        "completion_tokens": ct,
        "total_tokens": pt + ct,
    }


def build_app(
    served_name: str,
    fail_health_after: float = 0.0,
    token_delay: float = 0.0,
) -> web.Application:
    from gpustack_tpu.observability.tracing import trace_middleware

    # same trace hop contract as the real engine (engine/api_server.py):
    # hermetic e2es assert the full four-hop trace against this stub
    app = web.Application(middlewares=[trace_middleware("engine")])

    async def health(_request):
        if fail_health_after and time.time() - START > fail_health_after:
            return web.json_response({"status": "failing"}, status=503)
        return web.json_response({"status": "ok"})

    async def models(_request):
        return web.json_response({
            "object": "list",
            "data": [{"id": served_name, "object": "model",
                      "owned_by": "stub"}],
        })

    async def chat(request: web.Request):
        body = await request.json()
        prompt = " ".join(
            str(m.get("content", "")) for m in body.get("messages", [])
        )
        text = _gen_text(prompt, int(body.get("max_tokens", 16)))
        usage = _usage(prompt, text)
        rid = f"chatcmpl-{uuid.uuid4().hex[:12]}"
        if body.get("stream"):
            resp = web.StreamResponse(
                headers={"Content-Type": "text/event-stream"}
            )
            await resp.prepare(request)
            for piece in text.split(" "):
                chunk = {
                    "id": rid, "object": "chat.completion.chunk",
                    "model": served_name,
                    "choices": [{"index": 0,
                                 "delta": {"content": piece + " "},
                                 "finish_reason": None}],
                }
                await resp.write(
                    f"data: {json.dumps(chunk)}\n\n".encode()
                )
                # paced streaming (drain tests need a generation that is
                # genuinely in flight while the instance drains)
                await asyncio.sleep(token_delay)
            done = {
                "id": rid, "object": "chat.completion.chunk",
                "model": served_name,
                "choices": [{"index": 0, "delta": {},
                             "finish_reason": "stop"}],
                "usage": usage,
            }
            await resp.write(f"data: {json.dumps(done)}\n\n".encode())
            await resp.write(b"data: [DONE]\n\n")
            return resp
        return web.json_response({
            "id": rid, "object": "chat.completion",
            "created": int(time.time()), "model": served_name,
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": "stop",
            }],
            "usage": usage,
        })

    async def completions(request: web.Request):
        body = await request.json()
        prompt = str(body.get("prompt", ""))
        text = _gen_text(prompt, int(body.get("max_tokens", 16)))
        return web.json_response({
            "id": f"cmpl-{uuid.uuid4().hex[:12]}",
            "object": "text_completion",
            "created": int(time.time()), "model": served_name,
            "choices": [{"index": 0, "text": text,
                         "finish_reason": "stop"}],
            "usage": _usage(prompt, text),
        })

    async def metrics(_request):
        # vLLM metric names → exercised by worker/metrics_map.py
        lines = [
            "# TYPE vllm:num_requests_running gauge",
            "vllm:num_requests_running 0",
            "# TYPE vllm:prompt_tokens_total counter",
            f"vllm:prompt_tokens_total {STATS['prompt_tokens']}",
            "# TYPE vllm:generation_tokens_total counter",
            f"vllm:generation_tokens_total {STATS['generation_tokens']}",
            "# TYPE vllm:request_success_total counter",
            f"vllm:request_success_total {STATS['requests']}",
        ]
        return web.Response(text="\n".join(lines) + "\n")

    app.router.add_get("/health", health)
    app.router.add_get("/v1/models", models)
    app.router.add_post("/v1/chat/completions", chat)
    app.router.add_post("/v1/completions", completions)
    app.router.add_get("/metrics", metrics)
    return app


def main(argv=None) -> None:
    p = argparse.ArgumentParser("stub external engine")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--served-name", default="stub-model")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument(
        "--fail-health-after", type=float, default=0.0,
        help="seconds after which /health flips 503 (crash-path tests)",
    )
    p.add_argument(
        "--token-delay", type=float, default=0.0,
        help="seconds between streamed SSE chunks (drain tests)",
    )
    args = p.parse_args(argv)
    web.run_app(
        build_app(
            args.served_name, args.fail_health_after, args.token_delay
        ),
        host=args.host, port=args.port, print=None,
    )


if __name__ == "__main__":
    main()
