"""Reusable trace assertions for e2e and chaos suites.

The trace smoke test (tests/e2e/test_trace_smoke.py) and any chaos-run
postmortem share the same questions: did ONE trace id flow through
every hop, and did each hop record the phases it owes? These helpers
answer them from the two places traces land — log lines (``trace=…``)
and the in-memory stores served at ``GET /v2/debug/traces``.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Sequence, Set

TRACE_ID_RE = re.compile(r"\btrace=([0-9a-f]{32})\b")
COMPONENT_RE = re.compile(r"\bcomponent=([a-zA-Z_\-]+)\b")


def trace_ids_in(lines: Iterable[str]) -> Set[str]:
    ids: Set[str] = set()
    for line in lines:
        ids.update(TRACE_ID_RE.findall(line))
    return ids


def components_for_trace(
    lines: Iterable[str], trace_id: str
) -> Set[str]:
    """Components whose hop log line carries this trace id."""
    out: Set[str] = set()
    for line in lines:
        if trace_id not in line:
            continue
        m = COMPONENT_RE.search(line)
        if m:
            out.add(m.group(1))
        elif line.lstrip().startswith("access ") or " access " in line:
            out.add("server")
    return out


def assert_single_trace(
    lines: Iterable[str],
    expect_components: Sequence[str] = (),
) -> str:
    """Exactly one trace id across the given log lines, present in
    every expected component's hop line. Returns the trace id."""
    lines = list(lines)
    ids = trace_ids_in(lines)
    assert len(ids) == 1, (
        f"expected exactly one trace id across hops, saw {sorted(ids)}"
    )
    trace_id = next(iter(ids))
    seen = components_for_trace(lines, trace_id)
    missing = [c for c in expect_components if c not in seen]
    assert not missing, (
        f"trace {trace_id} missing from hops {missing} "
        f"(seen in: {sorted(seen)})"
    )
    return trace_id


def find_trace(
    items: List[Dict], trace_id: str, component: str = ""
) -> Optional[Dict]:
    """First /v2/debug/traces item matching trace id (and component)."""
    for entry in items:
        if entry.get("trace_id") != trace_id:
            continue
        if component and entry.get("component") != component:
            continue
        return entry
    return None


def assert_phases(entry: Dict, expected: Sequence[str]) -> None:
    """Every expected phase appears in the trace entry's spans with a
    non-negative duration."""
    assert entry, "no trace entry"
    spans = {p["phase"]: p for p in entry.get("spans", [])}
    missing = [p for p in expected if p not in spans]
    assert not missing, (
        f"trace {entry.get('trace_id')} ({entry.get('component')}) "
        f"missing phases {missing}; has {sorted(spans)}"
    )
    for name in expected:
        assert spans[name]["duration_ms"] >= 0.0, (
            f"phase {name} has negative duration"
        )
