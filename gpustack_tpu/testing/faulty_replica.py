"""Fault-injectable stand-in for a worker's data-plane surface.

Speaks the exact path the server's OpenAI proxy dials on a worker
(``/proxy/instances/{id}/v1/...``) and flips failure modes on command,
so the resilience layer (failover, circuit breaking, streaming safety,
load shedding — server/resilience.py) is testable without TPUs, real
engines, or even a ServeManager:

==================  =====================================================
mode                behavior
==================  =====================================================
``none``            healthy: deterministic OpenAI-style completions
                    (stream and non-stream), like testing/stub_engine.py
``error``           HTTP 500 JSON body (replica-side failure)
``hang``            accept the request, never send headers (wedged
                    engine — exercises the proxy's headers timeout)
``slow``            respond after ``delay_s`` (shed/backlog tests)
``die_mid_stream``  emit ``stream_chunks_before_death`` SSE chunks, then
                    abort the connection without ``[DONE]`` (the
                    must-never-retry case)
==================  =====================================================

Modes switch in-process via :attr:`FaultyReplica.mode` or over HTTP via
``POST /__fault__ {"mode": ..., "delay_s": ...}`` when the replica runs
as a separate process (``python -m gpustack_tpu.testing.faulty_replica``).
A full outage (connect refused) is simulated by :meth:`stop` — a closed
listener is the real thing, not an approximation.

``attempts`` counts data-plane requests received; the streaming-safety
test asserts it stays at 1 after a mid-stream death (no silent retry).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
import uuid
from typing import Optional

from aiohttp import web

VALID_MODES = ("none", "error", "hang", "slow", "die_mid_stream")


class FaultyReplica:
    def __init__(self, served_name: str = "stub-model"):
        self.served_name = served_name
        self.mode = "none"
        self.delay_s = 1.0
        self.stream_chunks_before_death = 2
        self.attempts = 0          # data-plane requests received
        self.port = 0
        self.app = web.Application()
        self.app.add_routes(
            [
                web.get("/healthz", self._healthz),
                web.post("/__fault__", self._set_fault),
                web.route(
                    "*",
                    "/proxy/instances/{id:\\d+}/{tail:.*}",
                    self._handle,
                ),
            ]
        )
        self._runner: Optional[web.AppRunner] = None

    # ---- lifecycle ------------------------------------------------------

    async def start(self, port: int = 0) -> int:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", port)
        await site.start()
        for sock in site._server.sockets:  # noqa: SLF001
            self.port = sock.getsockname()[1]
            break
        return self.port

    async def stop(self) -> None:
        """Close the listener — subsequent dials get connect-refused,
        the genuine article for dead-replica failover tests."""
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # ---- control --------------------------------------------------------

    async def _healthz(self, _request: web.Request) -> web.Response:
        return web.json_response(
            {"status": "ok", "mode": self.mode, "attempts": self.attempts}
        )

    async def _set_fault(self, request: web.Request) -> web.Response:
        body = await request.json()
        mode = body.get("mode", self.mode)
        if mode not in VALID_MODES:
            return web.json_response(
                {"error": f"unknown mode {mode!r} (valid: {VALID_MODES})"},
                status=400,
            )
        self.mode = mode
        if "delay_s" in body:
            self.delay_s = float(body["delay_s"])
        if "stream_chunks_before_death" in body:
            self.stream_chunks_before_death = int(
                body["stream_chunks_before_death"]
            )
        if body.get("reset_attempts"):
            self.attempts = 0
        return web.json_response({"mode": self.mode})

    # ---- data plane -----------------------------------------------------

    async def _handle(self, request: web.Request) -> web.StreamResponse:
        self.attempts += 1
        mode = self.mode
        if mode == "hang":
            # never respond; aiohttp cancels this handler when the
            # client gives up (the proxy's headers timeout)
            await asyncio.sleep(3600)
        if mode == "slow":
            await asyncio.sleep(self.delay_s)
        if mode == "error":
            return web.json_response(
                {"error": "injected replica failure"}, status=500
            )
        try:
            body = await request.json()
        except (json.JSONDecodeError, UnicodeDecodeError):
            body = {}
        prompt = " ".join(
            str(m.get("content", ""))
            for m in body.get("messages", [])
        ) or str(body.get("prompt", "") or "ok")
        words = (prompt.split() or ["ok"]) * 4
        text = "stub: " + " ".join(words[:8])
        usage = {
            "prompt_tokens": len(prompt.split()),
            "completion_tokens": len(text.split()),
            "total_tokens": len(prompt.split()) + len(text.split()),
        }
        rid = f"chatcmpl-{uuid.uuid4().hex[:12]}"
        if body.get("stream"):
            resp = web.StreamResponse(
                headers={"Content-Type": "text/event-stream"}
            )
            await resp.prepare(request)
            for n, piece in enumerate(text.split(" ")):
                if (
                    mode == "die_mid_stream"
                    and n >= self.stream_chunks_before_death
                ):
                    # abort without [DONE]: the client must see the
                    # truncation, never a silently retried duplicate
                    request.transport.close()
                    return resp
                chunk = {
                    "id": rid, "object": "chat.completion.chunk",
                    "model": self.served_name,
                    "choices": [{
                        "index": 0,
                        "delta": {"content": piece + " "},
                        "finish_reason": None,
                    }],
                }
                await resp.write(
                    f"data: {json.dumps(chunk)}\n\n".encode()
                )
                await asyncio.sleep(0)
            done = {
                "id": rid, "object": "chat.completion.chunk",
                "model": self.served_name,
                "choices": [{"index": 0, "delta": {},
                             "finish_reason": "stop"}],
                "usage": usage,
            }
            await resp.write(f"data: {json.dumps(done)}\n\n".encode())
            await resp.write(b"data: [DONE]\n\n")
            return resp
        return web.json_response({
            "id": rid, "object": "chat.completion",
            "created": int(time.time()), "model": self.served_name,
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": "stop",
            }],
            "usage": usage,
        })


def main(argv=None) -> None:
    p = argparse.ArgumentParser("fault-injectable replica")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--served-name", default="stub-model")
    p.add_argument("--mode", default="none", choices=VALID_MODES)
    args = p.parse_args(argv)
    replica = FaultyReplica(args.served_name)
    replica.mode = args.mode
    web.run_app(replica.app, host="127.0.0.1", port=args.port, print=None)


if __name__ == "__main__":
    main()
