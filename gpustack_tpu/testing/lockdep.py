"""Runtime lockdep: observed lock-order + hold-time discipline.

The static lock-order rule (analysis/rules/lock_order.py) sees the
acquisition edges the AST can prove; this harness sees the ones that
actually happen — including orders created dynamically (callbacks,
executors, locks passed across objects) that no static resolution
reaches. Modeled on the kernel's lockdep: every acquisition while
other locks are held adds an ordering edge, and a cycle in the merged
(static ∪ observed) graph is a deadlock some interleaving can reach,
reported even though this particular run never hung.

Usage (the chaos harness and tier-1 e2e smokes wire this up)::

    dep = LockDep(max_hold_s=1.0)
    dep.install()          # patches threading.Lock/RLock/Condition
    try:
        ...                # run the system under test
    finally:
        dep.uninstall()
    report = dep.report(static_edges=...)   # fails the run on findings

Tracked facts, per thread (a ``threading.local`` held-stack):

- **acquisition-order edges**: acquiring B while holding A records
  A → B. RLock re-entry on an already-held label records nothing (a
  self-edge is not an ordering).
- **hold times**: wall seconds between acquire and release; a hold
  beyond ``max_hold_s`` is a finding — the repo's locks guard tiny
  critical sections, so a long hold means file/device/network I/O
  crept under one. ``Condition.wait`` releases the lock, so parked
  time never counts as held.

Labels come from the construction site: ``self._mu = threading.Lock()``
in ``engine/kv_spill.py`` labels the lock
``gpustack_tpu/engine/kv_spill.py::_mu`` — the same namespace the
static graph uses once class qualifiers are normalized away
(:func:`normalize_label`), so the two graphs merge by plain set union.

Disabled (not installed) the module costs nothing: ``threading.Lock``
stays the original builtin and no shim exists on any acquire path.
"""

from __future__ import annotations

import linecache
import os
import re
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from gpustack_tpu.analysis.rules.lock_order import find_cycles

# ``self._wake = threading.Condition()`` / ``mu = threading.Lock()``
_ATTR_SITE_RE = re.compile(r"self\.(\w+)\s*(?::[^=]*?)?=")
_NAME_SITE_RE = re.compile(r"(\w+)\s*(?::[^=]*?)?=\s*\w+\.\w+\(")

_REPO_MARKER = "gpustack_tpu"


def normalize_label(label: str) -> str:
    """Strip the class qualifier from a static lock label so the two
    graphs share one namespace: ``path::Class.attr`` → ``path::attr``
    (runtime labels never see the class, only the assignment site)."""
    if "::" in label:
        path, _, name = label.partition("::")
        return f"{path}::{name.rsplit('.', 1)[-1]}"
    return label


def _site_rel(filename: str) -> str:
    """Repo-relative path for a construction site (best effort)."""
    norm = filename.replace(os.sep, "/")
    marker = f"/{_REPO_MARKER}/"
    idx = norm.rfind(marker)
    if idx >= 0:
        return norm[idx + 1:]
    return norm.rsplit("/", 1)[-1]


class LockDep:
    """Injectable lock monitor. ``install()`` patches the ``threading``
    factories; every lock constructed afterwards is tracked. Locks that
    predate ``install()`` stay raw (wrap them explicitly with
    :meth:`wrap` when a test needs them observed)."""

    def __init__(
        self,
        max_hold_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.max_hold_s = float(max_hold_s)
        self._clock = clock
        # saved originals — every internal lock below MUST come from
        # these, never from (possibly patched) threading.*
        self._orig_lock = threading.Lock
        self._orig_rlock = threading.RLock
        self._orig_condition = threading.Condition
        self._mu = self._orig_lock()
        self._installed = False
        # (src label, dst label) -> observation count
        self.edges: Dict[Tuple[str, str], int] = {}
        # (label, held seconds) beyond max_hold_s
        self.long_holds: List[Tuple[str, float]] = []
        self.locks_tracked = 0
        self._held = threading.local()

    # ---- install / uninstall -------------------------------------------

    def install(self) -> "LockDep":
        if self._installed:
            return self
        self._installed = True
        dep = self

        def make_lock():
            return _TrackedLock(dep, dep._label_site(), dep._orig_lock())

        def make_rlock():
            return _TrackedLock(
                dep, dep._label_site(), dep._orig_rlock(), reentrant=True
            )

        def make_condition(lock=None):
            return _TrackedCondition(dep, dep._label_site(), lock)

        threading.Lock = make_lock
        threading.RLock = make_rlock
        threading.Condition = make_condition
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        threading.Lock = self._orig_lock
        threading.RLock = self._orig_rlock
        threading.Condition = self._orig_condition
        self._installed = False

    def __enter__(self) -> "LockDep":
        return self.install()

    def __exit__(self, *exc: Any) -> None:
        self.uninstall()

    def wrap(self, lock: Any, name: str) -> "_TrackedLock":
        """Explicitly track an existing lock under ``name`` (unit
        tests; locks constructed before install())."""
        return _TrackedLock(self, name, lock)

    # ---- labeling -------------------------------------------------------

    def _label_site(self) -> str:
        """Label a lock by its construction site: the first caller
        frame outside this module, ``{rel}::{attr}`` when the source
        line is an attribute/name assignment, ``{rel}:{line}``
        otherwise."""
        f = sys._getframe(1)
        while f is not None and f.f_globals.get("__name__") == __name__:
            f = f.f_back
        if f is None:
            return "<unknown>"
        rel = _site_rel(f.f_code.co_filename)
        line = linecache.getline(f.f_code.co_filename, f.f_lineno)
        m = _ATTR_SITE_RE.search(line) or _NAME_SITE_RE.search(line)
        if m:
            return f"{rel}::{m.group(1)}"
        return f"{rel}:{f.f_lineno}"

    # ---- per-thread bookkeeping ----------------------------------------

    def _stack(self) -> List[Tuple[str, float]]:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = self._held.stack = []
        return stack

    def held_labels(self) -> List[str]:
        """This thread's currently-held lock labels, oldest first."""
        return [label for label, _ in self._stack()]

    def note_acquired(self, label: str) -> None:
        stack = self._stack()
        if any(h == label for h, _ in stack):
            # RLock re-entry: not a new ordering, not a new hold
            return
        now = self._clock()
        if stack:
            with self._mu:
                for h, _ in stack:
                    key = (h, label)
                    self.edges[key] = self.edges.get(key, 0) + 1
        stack.append((label, now))

    def note_released(self, label: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == label:
                _, t0 = stack.pop(i)
                held_s = self._clock() - t0
                if held_s > self.max_hold_s:
                    with self._mu:
                        self.long_holds.append((label, held_s))
                return

    # ---- verdict --------------------------------------------------------

    def report(
        self,
        static_edges: Optional[Dict[Tuple[str, str], Any]] = None,
    ) -> Dict[str, Any]:
        """Merge observed edges with the static graph (labels
        normalized) and return the findings dict the chaos report
        embeds. Empty ``findings`` = discipline held."""
        with self._mu:
            observed = dict(self.edges)
            long_holds = list(self.long_holds)
        merged = {
            (normalize_label(a), normalize_label(b))
            for a, b in observed
        }
        static_count = 0
        if static_edges:
            for a, b in static_edges:
                merged.add((normalize_label(a), normalize_label(b)))
                static_count += 1
        cycles = find_cycles(merged)
        findings: List[Dict[str, Any]] = []
        for cycle in cycles:
            findings.append({
                "kind": "lock-cycle",
                "cycle": cycle + [cycle[0]],
            })
        for label, held_s in long_holds:
            findings.append({
                "kind": "long-hold",
                "lock": label,
                "held_s": round(held_s, 4),
                "max_hold_s": self.max_hold_s,
            })
        return {
            "locks_tracked": self.locks_tracked,
            "observed_edges": len(observed),
            "static_edges": static_count,
            "cycles": cycles,
            "long_holds": [
                {"lock": lbl, "held_s": round(s, 4)}
                for lbl, s in long_holds
            ],
            "findings": findings,
        }


def static_acquisition_edges(
    root: Optional[str] = None,
) -> Dict[Tuple[str, str], Any]:
    """The analyzer's static lock graph for ``root`` (default: the
    repo this package was imported from) — the other half of
    :meth:`LockDep.report`'s merge."""
    from gpustack_tpu.analysis.core import Project
    from gpustack_tpu.analysis.rules.lock_order import acquisition_edges

    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ))
    return acquisition_edges(Project(root))


class _TrackedLock:
    """Proxy around a real lock. Only acquire/release (and the context
    protocol) are intercepted; everything else delegates."""

    def __init__(
        self,
        dep: LockDep,
        label: str,
        inner: Any,
        reentrant: bool = False,
    ):
        self._dep = dep
        self._label = label
        self._inner = inner
        self._reentrant = reentrant
        dep.locks_tracked += 1

    def acquire(self, *args: Any, **kwargs: Any) -> bool:
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._dep.note_acquired(self._label)
        return got

    def release(self) -> None:
        self._inner.release()
        self._dep.note_released(self._label)

    def __enter__(self) -> "_TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"<tracked {self._label} {self._inner!r}>"


class _TrackedCondition:
    """Condition variable whose lock side is a :class:`_TrackedLock`.
    ``wait`` unwinds the held bookkeeping while parked — parked time is
    not held time — and restores it on wakeup."""

    def __init__(self, dep: LockDep, label: str, lock: Any = None):
        if isinstance(lock, _TrackedLock):
            self._lock = lock
        elif lock is not None:
            self._lock = _TrackedLock(dep, label, lock)
        else:
            # plain Condition() default: an RLock, from the ORIGINAL
            # factory (the patched one would double-track)
            self._lock = _TrackedLock(
                dep, label, dep._orig_rlock(), reentrant=True
            )
        # the real condition binds the RAW lock: its wait() must
        # release the actual mutex, not the proxy
        self._cond = dep._orig_condition(self._lock._inner)
        self._dep = dep

    def acquire(self, *args: Any, **kwargs: Any) -> bool:
        return self._lock.acquire(*args, **kwargs)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> "_TrackedCondition":
        self._lock.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._lock.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        self._dep.note_released(self._lock._label)
        try:
            return self._cond.wait(timeout)
        finally:
            self._dep.note_acquired(self._lock._label)

    def wait_for(
        self,
        predicate: Callable[[], Any],
        timeout: Optional[float] = None,
    ) -> Any:
        # reimplemented over OUR wait() so parked time stays untracked
        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = time.monotonic() + timeout
                waittime = endtime - time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def __repr__(self) -> str:
        return f"<tracked-cond {self._lock._label}>"
