"""Strict Prometheus text-format (exposition 0.0.4) parser for tests.

The exporters are hand-built string emitters, so nothing at runtime
guarantees the wire format is parseable by a real scraper. This parser
is deliberately STRICTER than Prometheus itself and raises
:class:`ExpositionError` on anything a hand-rolled emitter typically
gets wrong:

- a sample line that does not fully parse (unquoted/unescaped label
  values, trailing garbage, non-numeric value);
- a ``# TYPE`` repeated for the same family, appearing AFTER the
  family's first sample, or naming an invalid kind;
- histogram family violations: non-cumulative ``_bucket`` counts, a
  missing ``+Inf`` bucket, ``+Inf`` != ``_count``, missing
  ``_sum``/``_count`` series.

Untyped samples are allowed (the worker relays engine metrics without
re-declaring them) — but once a family IS declared, its declaration
must precede its samples.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

VALID_KINDS = ("counter", "gauge", "histogram", "summary", "untyped")

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_TYPE_RE = re.compile(rf"^#\s*TYPE\s+({_NAME})\s+(\S+)\s*$")
_SAMPLE_RE = re.compile(
    rf"^({_NAME})(\{{(.*)\}})?\s+(-?[0-9.eE+\-]+|NaN|[+-]Inf)"
    r"(\s+-?[0-9]+)?\s*$"
)
_LABEL_RE = re.compile(rf'({_NAME})="((?:[^"\\\n]|\\["\\n])*)"')


class ExpositionError(AssertionError):
    pass


class Sample:
    __slots__ = ("name", "labels", "value", "line_no")

    def __init__(self, name, labels, value, line_no):
        self.name = name
        self.labels = labels
        self.value = value
        self.line_no = line_no


def _parse_labels(raw: str, line_no: int, line: str) -> Dict[str, str]:
    labels: Dict[str, str] = {}
    pos = 0
    while pos < len(raw):
        m = _LABEL_RE.match(raw, pos)
        if m is None:
            raise ExpositionError(
                f"line {line_no}: malformed label pair at char {pos} "
                f"in: {line!r}"
            )
        if m.group(1) in labels:
            raise ExpositionError(
                f"line {line_no}: duplicate label {m.group(1)!r} "
                f"in: {line!r}"
            )
        labels[m.group(1)] = m.group(2)
        pos = m.end()
        if pos < len(raw):
            if raw[pos] != ",":
                raise ExpositionError(
                    f"line {line_no}: expected ',' between labels "
                    f"in: {line!r}"
                )
            pos += 1
    return labels


def _family_of(name: str) -> str:
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


def parse_exposition(
    text: str,
) -> Tuple[List[Sample], Dict[str, str]]:
    """Parse strictly; returns (samples, {family: kind}). Raises
    :class:`ExpositionError` on any format violation."""
    samples: List[Sample] = []
    types: Dict[str, str] = {}
    seen_sample_families = set()
    for line_no, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("#"):
            m = _TYPE_RE.match(line)
            if m is None:
                if re.match(r"^#\s*TYPE\b", line):
                    raise ExpositionError(
                        f"line {line_no}: malformed TYPE line: {line!r}"
                    )
                continue           # HELP/comment lines pass through
            name, kind = m.groups()
            if kind not in VALID_KINDS:
                raise ExpositionError(
                    f"line {line_no}: invalid TYPE kind {kind!r} "
                    f"for {name}"
                )
            if name in types:
                raise ExpositionError(
                    f"line {line_no}: duplicate TYPE declaration "
                    f"for {name}"
                )
            if name in seen_sample_families:
                raise ExpositionError(
                    f"line {line_no}: TYPE for {name} appears after "
                    f"its first sample"
                )
            types[name] = kind
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ExpositionError(
                f"line {line_no}: unparseable sample line: {line!r}"
            )
        name, braces, raw_labels, value, _ts = m.groups()
        labels = (
            _parse_labels(raw_labels, line_no, line) if braces else {}
        )
        try:
            val = float(value)
        except ValueError:
            raise ExpositionError(
                f"line {line_no}: non-numeric value {value!r}"
            ) from None
        samples.append(Sample(name, labels, val, line_no))
        seen_sample_families.add(_family_of(name))
        seen_sample_families.add(name)
    return samples, types


def check_histograms(
    samples: List[Sample], types: Dict[str, str]
) -> None:
    """Per declared histogram family and label set: buckets cumulative,
    ``+Inf`` present and equal to ``_count``, ``_sum`` present."""
    for family, kind in types.items():
        if kind != "histogram":
            continue
        # group by the non-le label set
        buckets: Dict[Tuple, List[Tuple[float, float]]] = {}
        counts: Dict[Tuple, float] = {}
        sums: Dict[Tuple, float] = {}
        for s in samples:
            base_key = tuple(sorted(
                (k, v) for k, v in s.labels.items() if k != "le"
            ))
            if s.name == family + "_bucket":
                le = s.labels.get("le")
                if le is None:
                    raise ExpositionError(
                        f"line {s.line_no}: {s.name} sample without "
                        f"an 'le' label"
                    )
                ub = math.inf if le == "+Inf" else float(le)
                buckets.setdefault(base_key, []).append((ub, s.value))
            elif s.name == family + "_count":
                counts[base_key] = s.value
            elif s.name == family + "_sum":
                sums[base_key] = s.value
        for key, series in buckets.items():
            ordered = sorted(series, key=lambda p: p[0])
            last = -1.0
            for ub, cum in ordered:
                if cum < last:
                    raise ExpositionError(
                        f"{family}{dict(key)}: bucket le={ub} count "
                        f"{cum} < previous {last} (not cumulative)"
                    )
                last = cum
            if not ordered or ordered[-1][0] != math.inf:
                raise ExpositionError(
                    f"{family}{dict(key)}: no +Inf bucket"
                )
            if key not in counts:
                raise ExpositionError(
                    f"{family}{dict(key)}: missing _count series"
                )
            if key not in sums:
                raise ExpositionError(
                    f"{family}{dict(key)}: missing _sum series"
                )
            if ordered[-1][1] != counts[key]:
                raise ExpositionError(
                    f"{family}{dict(key)}: +Inf bucket "
                    f"{ordered[-1][1]} != _count {counts[key]}"
                )


def assert_well_formed(
    text: str, require_histograms: Optional[List[str]] = None
) -> Tuple[List[Sample], Dict[str, str]]:
    """One-call strict validation; optionally require specific
    histogram families to be declared AND populated."""
    samples, types = parse_exposition(text)
    check_histograms(samples, types)
    for family in require_histograms or ():
        if types.get(family) != "histogram":
            raise ExpositionError(
                f"{family} is not declared as a histogram "
                f"(declared: {types.get(family)!r})"
            )
        if not any(s.name == family + "_count" for s in samples):
            raise ExpositionError(f"{family} has no samples")
    return samples, types
