"""Dialect-aware SQL fragments.

Like PK_CLAUSE (orm/record.py), these are the ONLY places dialect-specific
spellings may live; query code composes them instead of hardcoding
sqlite-isms. tests/orm/test_dialect_conformance.py enforces this two
ways: the ORM statement trace rejects hardcoded constructs, and a source
scan asserts ``json_extract`` appears nowhere outside this module.
"""

from __future__ import annotations

_JSON_NUM = {
    # sqlite: json1 extract; numeric affinity handles SUM/ORDER
    "sqlite": "json_extract({col}, '$.{field}')",
    # postgres: jsonb text accessor + explicit numeric cast
    "postgres": "(({col})::jsonb ->> '{field}')::numeric",
    # mysql: unquoted extract; implicit numeric coercion in aggregates
    "mysql": "JSON_UNQUOTE(JSON_EXTRACT({col}, '$.{field}'))",
}

_JSON_TEXT = {
    "sqlite": "json_extract({col}, '$.{field}')",
    "postgres": "(({col})::jsonb ->> '{field}')",
    "mysql": "JSON_UNQUOTE(JSON_EXTRACT({col}, '$.{field}'))",
}

# single-field JSON writer: each spelling consumes exactly ONE bind
# parameter — the new value as JSON text (``json.dumps``) — and yields
# the whole updated document, so call sites compose
# ``SET data = <json_set(...)>``. Every dialect PARSES the bind as
# JSON, so a numeric value stays a JSON number on all three (a raw
# text bind would store "1.5" as a string on postgres but 1.5 as a
# number on sqlite/mysql, silently diverging document shapes).
_JSON_SET = {
    "sqlite": "json_set({col}, '$.{field}', json(?))",
    "postgres": (
        "jsonb_set(({col})::jsonb, '{{{field}}}', "
        "(?)::jsonb)::text"
    ),
    "mysql": "JSON_SET({col}, '$.{field}', CAST(? AS JSON))",
}

DIALECTS = tuple(_JSON_NUM)


def json_num(field: str, col: str = "data", dialect: str = "sqlite") -> str:
    """Numeric JSON field accessor for aggregates (SUM/ORDER BY)."""
    return _JSON_NUM[dialect].format(col=col, field=field)


def json_text(field: str, col: str = "data", dialect: str = "sqlite") -> str:
    """Textual JSON field accessor."""
    return _JSON_TEXT[dialect].format(col=col, field=field)


def json_set(field: str, col: str = "data", dialect: str = "sqlite") -> str:
    """Single-field JSON document writer; binds one ``?`` (the value)."""
    return _JSON_SET[dialect].format(col=col, field=field)
