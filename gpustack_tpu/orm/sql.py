"""Dialect-aware SQL fragments.

Like PK_CLAUSE (orm/record.py), these are the ONLY places dialect-specific
spellings may live; query code composes them instead of hardcoding
sqlite-isms. tests/orm/test_dialect_conformance.py enforces this two
ways: the ORM statement trace rejects hardcoded constructs, and a source
scan asserts ``json_extract`` appears nowhere outside this module.
"""

from __future__ import annotations

_JSON_NUM = {
    # sqlite: json1 extract; numeric affinity handles SUM/ORDER
    "sqlite": "json_extract({col}, '$.{field}')",
    # postgres: jsonb text accessor + explicit numeric cast
    "postgres": "(({col})::jsonb ->> '{field}')::numeric",
    # mysql: unquoted extract; implicit numeric coercion in aggregates
    "mysql": "JSON_UNQUOTE(JSON_EXTRACT({col}, '$.{field}'))",
}

_JSON_TEXT = {
    "sqlite": "json_extract({col}, '$.{field}')",
    "postgres": "(({col})::jsonb ->> '{field}')",
    "mysql": "JSON_UNQUOTE(JSON_EXTRACT({col}, '$.{field}'))",
}

# single-field JSON writer: each spelling consumes exactly ONE bind
# parameter — the new value as JSON text (``json.dumps``) — and yields
# the whole updated document, so call sites compose
# ``SET data = <json_set(...)>``. Every dialect PARSES the bind as
# JSON, so a numeric value stays a JSON number on all three (a raw
# text bind would store "1.5" as a string on postgres but 1.5 as a
# number on sqlite/mysql, silently diverging document shapes).
_JSON_SET = {
    "sqlite": "json_set({col}, '$.{field}', json(?))",
    "postgres": (
        "jsonb_set(({col})::jsonb, '{{{field}}}', "
        "(?)::jsonb)::text"
    ),
    "mysql": "JSON_SET({col}, '$.{field}', CAST(? AS JSON))",
}

# HA lease election (server/coordinator.py): atomic conditional upsert
# that steals ONLY an expired lease and bumps the monotonic fencing
# ``epoch`` on every acquisition (never on renewal). sqlite/postgres
# share the ON CONFLICT .. DO UPDATE .. WHERE spelling; mysql has no
# conditional upsert WHERE, so each assignment re-checks expiry with
# IF() — ``expires_at`` is assigned LAST so the earlier assignments
# still read the pre-update value (mysql evaluates left-to-right).
# Bind parameters differ per dialect; compose them with
# :func:`lease_upsert_params`, never by hand.
_LEASE_UPSERT = {
    "sqlite": (
        "INSERT INTO leadership (id, holder, expires_at, epoch) "
        "VALUES (1, ?, ?, 1) "
        "ON CONFLICT(id) DO UPDATE SET "
        "holder = excluded.holder, "
        "expires_at = excluded.expires_at, "
        "epoch = leadership.epoch + 1 "
        "WHERE leadership.expires_at < ?"
    ),
    "postgres": (
        "INSERT INTO leadership (id, holder, expires_at, epoch) "
        "VALUES (1, ?, ?, 1) "
        "ON CONFLICT(id) DO UPDATE SET "
        "holder = excluded.holder, "
        "expires_at = excluded.expires_at, "
        "epoch = leadership.epoch + 1 "
        "WHERE leadership.expires_at < ?"
    ),
    "mysql": (
        "INSERT INTO leadership (id, holder, expires_at, epoch) "
        "VALUES (1, ?, ?, 1) "
        "ON DUPLICATE KEY UPDATE "
        "epoch = IF(expires_at < ?, epoch + 1, epoch), "
        "holder = IF(expires_at < ?, VALUES(holder), holder), "
        "expires_at = IF(expires_at < ?, VALUES(expires_at), expires_at)"
    ),
}

# bind order per spelling (names resolved by lease_upsert_params)
_LEASE_UPSERT_PARAMS = {
    "sqlite": ("holder", "expires", "now"),
    "postgres": ("holder", "expires", "now"),
    "mysql": ("holder", "expires", "now", "now", "now"),
}

# Fencing guard (orm/fencing.py): appended to a leader-stamped write's
# WHERE so a write carrying an epoch older than the current lease
# rejects ATOMICALLY in the same statement. One bind: the writer's
# epoch. The spelling is already dialect-generic (plain NOT EXISTS
# subquery) — kept here anyway so every HA SQL fragment has one home.
_FENCE_GUARD = {
    "sqlite": (
        "NOT EXISTS (SELECT 1 FROM leadership "
        "WHERE id = 1 AND epoch > ?)"
    ),
    "postgres": (
        "NOT EXISTS (SELECT 1 FROM leadership "
        "WHERE id = 1 AND epoch > ?)"
    ),
    "mysql": (
        "NOT EXISTS (SELECT 1 FROM leadership "
        "WHERE id = 1 AND epoch > ?)"
    ),
}

# a SELECT without a table reference may not carry WHERE on mysql —
# guarded INSERT ... SELECT needs FROM DUAL there (8.0.19+ spelling)
_DUAL_FROM = {"sqlite": "", "postgres": "", "mysql": " FROM DUAL"}

DIALECTS = tuple(_JSON_NUM)


def json_num(field: str, col: str = "data", dialect: str = "sqlite") -> str:
    """Numeric JSON field accessor for aggregates (SUM/ORDER BY)."""
    return _JSON_NUM[dialect].format(col=col, field=field)


def json_text(field: str, col: str = "data", dialect: str = "sqlite") -> str:
    """Textual JSON field accessor."""
    return _JSON_TEXT[dialect].format(col=col, field=field)


def json_set(field: str, col: str = "data", dialect: str = "sqlite") -> str:
    """Single-field JSON document writer; binds one ``?`` (the value)."""
    return _JSON_SET[dialect].format(col=col, field=field)


def lease_upsert(dialect: str = "sqlite") -> str:
    """Conditional lease-steal upsert with fencing-epoch bump."""
    return _LEASE_UPSERT[dialect]


def lease_upsert_params(
    holder: str, expires: float, now: float, dialect: str = "sqlite"
) -> tuple:
    """Bind tuple matching :func:`lease_upsert`'s per-dialect order."""
    values = {"holder": holder, "expires": expires, "now": now}
    return tuple(values[name] for name in _LEASE_UPSERT_PARAMS[dialect])


def fence_guard(dialect: str = "sqlite") -> str:
    """Stale-epoch rejection clause; binds one ``?`` (writer's epoch)."""
    return _FENCE_GUARD[dialect]


def dual_from(dialect: str = "sqlite") -> str:
    """Table-less SELECT filler for guarded INSERT ... SELECT."""
    return _DUAL_FROM[dialect]
