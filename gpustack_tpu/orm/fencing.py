"""Epoch write-fencing for HA leader-only writers.

TTL-lease leader election (server/coordinator.py) cannot, by itself,
stop a deposed-but-not-yet-exited leader from writing: between losing
the lease and noticing (up to ttl/3 later — or much later if its event
loop stalled), its controllers keep issuing whole-document writes that
would clobber the successor's state. The classic fix is a fencing
token: every lease acquisition bumps a monotonic ``epoch`` on the lease
row, leader-only tasks stamp their writes with the epoch they acquired,
and the storage layer rejects any write carrying an epoch older than
the current lease — atomically, in the same statement as the write, so
no check-then-act race remains.

The stamp travels via a :class:`contextvars.ContextVar`: the server
sets it inside the leadership callback, so every task the callback
starts (scheduler, controllers, rescuer, rollout, autoscaler,
collectors) inherits it, while request handlers and follower tasks stay
unfenced (API writes are legitimate on any server). ``Record``'s write
methods (orm/record.py) read the stamp and compose the guard clause.

Module-level counters/hooks (not per-instance) because a process is one
server in production; the in-process multi-server chaos harness reads
them as cluster-wide totals, which is what its invariants want anyway.
"""

from __future__ import annotations

import contextvars
import threading
from typing import Callable, Dict, Optional

# epoch this task's writes are stamped with; None = unfenced
_fence_epoch: contextvars.ContextVar[Optional[int]] = contextvars.ContextVar(
    "gpustack_tpu_fence_epoch", default=None
)

# lossless audit tap for the chaos harness's no-stale-epoch-write
# invariant: called for every fenced write attempt with
# (kind, record_id, write_epoch, lease_epoch_at_statement, landed).
# ``lease_epoch_at_statement`` is read on the same connection inside the
# same implicit transaction as the guarded statement, so it is exactly
# the epoch the guard judged against. May be called from the DB writer
# thread — handlers must be thread-safe and non-raising.
audit_hook: Optional[Callable[[str, int, int, int, bool], None]] = None

_lock = threading.Lock()
# kind -> rejected-write count (gpustack_ha_fenced_writes_total)
_fenced: Dict[str, int] = {}


def set_fence(epoch: int) -> None:
    """Stamp this context (and every task it spawns) with ``epoch``."""
    _fence_epoch.set(int(epoch))


def clear_fence() -> None:
    _fence_epoch.set(None)


def fence_epoch() -> Optional[int]:
    return _fence_epoch.get()


def record_fenced(kind: str) -> None:
    """Count one rejected stale-epoch write (called by orm/record.py)."""
    with _lock:
        _fenced[kind] = _fenced.get(kind, 0) + 1


def fenced_writes() -> Dict[str, int]:
    with _lock:
        return dict(_fenced)


def fenced_writes_total() -> int:
    with _lock:
        return sum(_fenced.values())


def reset_counters() -> None:
    """Test helper: isolate per-test fenced-write assertions."""
    with _lock:
        _fenced.clear()
