"""Transactional change-log appends: replication events commit WITH
their data write.

PR 10 propagated writes to HA peers through a shared ``change_log``
table fed from an in-memory outbox (a bus tap enqueued, a ttl/6 loop
flushed). That left a crash window: a SIGKILL'd leader lost every event
enqueued since its last flush, and peers re-learned those rows only
when they were next touched — the recorded durability residual this
module closes.

Now the change-log INSERT is folded into the SAME transaction as the
guarded data write (orm/record.py ``create``/``save``/``delete`` call
:func:`append_change` between the data statement and ``commit``), so a
write is either fully replicated-on-commit or not committed at all.
There is nothing left to lose in a crash: the coordinator's bus tap
survives only as a post-commit no-op (and ``_flush_outbox`` as a
migration shim for non-transactional bindings — plugin coordinators
without a ``changelog_origin`` on their Database).

``Record.set_field`` deliberately does NOT append: it is the
event-less hot-path write shape (autoscaler wake markers, the
heartbeat/status write combiner) whose whole point is that thousands
of workers' liveness writes generate neither watch events nor
replication traffic.
"""

from __future__ import annotations

import json
import time
from typing import Optional

# analytics/collector rows are written per-request or per-sweep and
# only ever READ straight from the shared DB (usage queries, archiver)
# — replicating them through the change log would make every proxied
# request a cross-server event at exactly the scale HA exists for
REPLICATION_SKIP_KINDS = frozenset({
    "model_usage", "usage_archive", "resource_event", "system_load",
})


def change_log_ddl(pk_clause: str) -> str:
    """The shared replication table (one per cluster DB)."""
    return (
        "CREATE TABLE IF NOT EXISTS change_log ("
        f"{pk_clause}, "
        "origin TEXT, kind TEXT, record_id INTEGER, "
        "event_type TEXT, changes TEXT, created_at REAL)"
    )


def encode_changes(changes) -> Optional[str]:
    """Changed-field diff as JSON text (peers' changes-gated consumers
    need WHICH fields moved, not just that something did)."""
    if not changes:
        return None
    try:
        return json.dumps(changes)
    except (TypeError, ValueError):
        return None


def append_change(
    conn,
    origin: str,
    kind: str,
    event_type: str,
    record_id: int,
    changes_json: Optional[str] = None,
    now: Optional[float] = None,
) -> bool:
    """Append one replication entry on the DB thread, inside the data
    write's still-open transaction. Returns False for kinds that never
    replicate. Raising here aborts the caller's commit — a data write
    whose replication event cannot be recorded must not land half."""
    if not origin or not kind or kind in REPLICATION_SKIP_KINDS:
        return False
    conn.execute(
        "INSERT INTO change_log "
        "(origin, kind, record_id, event_type, changes, created_at) "
        "VALUES (?, ?, ?, ?, ?, ?)",
        (
            origin, kind, int(record_id), event_type, changes_json,
            time.time() if now is None else now,
        ),
    )
    return True
