"""Async sqlite database: single writer thread, WAL, migrations.

All sqlite calls run on one dedicated thread (sqlite serializes writers
anyway); async callers await a future. This gives true async semantics to
the aiohttp control plane without aiosqlite (absent from the image).
"""

from __future__ import annotations

import asyncio
import json
import logging
import queue
import sqlite3
import threading
from typing import Any, Callable, Iterable, List, Optional, Tuple

logger = logging.getLogger(__name__)


class DatabaseClosedError(RuntimeError):
    """The ONE drain-contract error for work queued behind a shutdown:
    raised by :meth:`Database.run` once ``close()`` flagged the writer
    thread down, set on every future still queued when the thread
    exits, and re-raised by consumers with their own pending queues
    (the control write combiner) so a write buffered behind shutdown
    fails LOUDLY to its caller instead of silently dropping or hanging
    an awaiter forever."""

    def __init__(self, what: str):
        super().__init__(f"{what} is closed; queued write dropped")


class Database:
    """One sqlite file (or ':memory:') + a writer thread + migrations."""

    def __init__(self, path: str = ":memory:", dialect: str = "sqlite"):
        from gpustack_tpu.orm.sql import DIALECTS

        if dialect not in DIALECTS:
            raise ValueError(f"unknown SQL dialect {dialect!r}")
        self.path = path
        self.dialect = dialect
        self.closed = False
        # HA replication: when set (LeaseCoordinator.start), Record
        # write transactions append a change_log entry stamped with
        # this server identity IN the same commit (orm/changelog.py)
        self.changelog_origin = ""
        # round-trips to the writer thread (run/execute/execute_sync):
        # the scale suites' "query count" — a batched executemany is
        # ONE op here, which is exactly the coalescing being measured
        self.op_count = 0
        # committed transactions that contained at least one
        # INSERT/UPDATE/DELETE (sqlite trace callback, writer thread):
        # the scale suites' "DB write rate" — a 1000-row batched flush
        # is ONE write transaction
        self.write_txn_count = 0
        self._txn_dirty = False
        self._work: "queue.Queue[Optional[Tuple[Callable, asyncio.Future, asyncio.AbstractEventLoop]]]" = (
            queue.Queue()
        )
        self._thread = threading.Thread(
            target=self._run, name="db-writer", daemon=True
        )
        self._conn: Optional[sqlite3.Connection] = None
        self._started = threading.Event()
        self._thread.start()
        self._started.wait(10)

    # ---- worker thread --------------------------------------------------

    def _run(self) -> None:
        # generous busy timeout: HA runs several server processes (or
        # the in-process chaos harness's several Database instances)
        # against ONE sqlite file — WAL serializes writers, and a
        # losing writer must wait, not throw "database is locked"
        self._conn = sqlite3.connect(
            self.path, check_same_thread=True, timeout=30.0
        )
        self._conn.row_factory = sqlite3.Row
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA foreign_keys=ON")
        # write-transaction accounting (scale suites): the trace fires
        # per executed statement on THIS thread; a commit that saw any
        # DML since the last boundary counts once. Tests that install
        # their own trace callback (dialect conformance) simply pause
        # this counter — it is telemetry, not a correctness feature.
        self._conn.set_trace_callback(self._trace_stmt)
        self._started.set()
        while True:
            item = self._work.get()
            if item is None:
                break
            fn, fut, loop = item
            try:
                result = fn(self._conn)
            except Exception as e:  # propagate to awaiting caller
                loop.call_soon_threadsafe(self._set_exc, fut, e)
            else:
                loop.call_soon_threadsafe(self._set_result, fut, result)
        self._conn.close()
        # items that slipped in behind the shutdown sentinel must fail,
        # not hang their awaiting callers forever
        self._fail_pending()

    def _fail_pending(self) -> None:
        """Resolve every still-queued work item with a closed error.
        Only safe once the worker thread is no longer consuming."""
        if self._thread.is_alive() and (
            threading.current_thread() is not self._thread
        ):
            return
        while True:
            try:
                item = self._work.get_nowait()
            except queue.Empty:
                return
            if item is None:
                continue
            _fn, fut, loop = item
            try:
                loop.call_soon_threadsafe(
                    self._set_exc, fut,
                    DatabaseClosedError(f"database {self.path!r}"),
                )
            except RuntimeError:
                pass  # caller's loop already gone

    def _trace_stmt(self, sql: str) -> None:
        head = sql.lstrip().upper()
        if head.startswith(("INSERT", "UPDATE", "DELETE", "REPLACE")):
            self._txn_dirty = True
        elif head.startswith("COMMIT"):
            if self._txn_dirty:
                self.write_txn_count += 1
            self._txn_dirty = False
        elif head.startswith("ROLLBACK"):
            self._txn_dirty = False

    @staticmethod
    def _set_result(fut: asyncio.Future, result: Any) -> None:
        if not fut.done():
            fut.set_result(result)

    @staticmethod
    def _set_exc(fut: asyncio.Future, exc: Exception) -> None:
        if not fut.done():
            fut.set_exception(exc)

    # ---- dialect-bound SQL fragments ------------------------------------
    # Query code MUST use these (not orm.sql's module functions with
    # their sqlite default) so the active connection's dialect reaches
    # every call site — advisor r4: the default-dialect shortcut left
    # the abstraction unwired and a postgres/mysql deployment's usage
    # queries would all mis-spell.

    def json_num(self, field: str, col: str = "data") -> str:
        from gpustack_tpu.orm import sql

        return sql.json_num(field, col, self.dialect)

    def json_text(self, field: str, col: str = "data") -> str:
        from gpustack_tpu.orm import sql

        return sql.json_text(field, col, self.dialect)

    def json_set(self, field: str, col: str = "data") -> str:
        from gpustack_tpu.orm import sql

        return sql.json_set(field, col, self.dialect)

    def lease_upsert(self) -> str:
        from gpustack_tpu.orm import sql

        return sql.lease_upsert(self.dialect)

    def lease_upsert_params(
        self, holder: str, expires: float, now: float
    ) -> Tuple:
        from gpustack_tpu.orm import sql

        return sql.lease_upsert_params(
            holder, expires, now, self.dialect
        )

    def fence_guard(self) -> str:
        from gpustack_tpu.orm import sql

        return sql.fence_guard(self.dialect)

    def dual_from(self) -> str:
        from gpustack_tpu.orm import sql

        return sql.dual_from(self.dialect)

    # ---- async API ------------------------------------------------------

    async def run(self, fn: Callable[[sqlite3.Connection], Any]) -> Any:
        """Run ``fn(conn)`` on the db thread; commit is the fn's concern."""
        if self.closed:
            # the writer thread is gone: queueing would await a future
            # nothing will ever resolve (a stopped HA server's handle)
            raise DatabaseClosedError(f"database {self.path!r}")
        self.op_count += 1
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._work.put((fn, fut, loop))
        if self.closed:
            # close() raced the put: our item may sit BEHIND the
            # shutdown sentinel where the worker never looks — make
            # sure someone resolves it (idempotent: _set_exc/_set_result
            # both check fut.done())
            self._fail_pending()
        return await fut

    async def execute(
        self, sql: str, params: Iterable[Any] = ()
    ) -> List[sqlite3.Row]:
        def go(conn: sqlite3.Connection):
            cur = conn.execute(sql, tuple(params))
            rows = cur.fetchall()
            conn.commit()
            return rows

        return await self.run(go)

    def execute_sync(
        self, sql: str, params: Iterable[Any] = ()
    ) -> List[sqlite3.Row]:
        """Blocking variant for startup/migration code (no loop running)."""
        done = threading.Event()
        box: List[Any] = [None, None]

        def go(conn: sqlite3.Connection):
            try:
                cur = conn.execute(sql, tuple(params))
                rows = cur.fetchall()
                conn.commit()
                box[0] = rows
            except Exception as e:
                box[1] = e
            finally:
                done.set()

        # Bypass the futures machinery (no event loop required).
        self.op_count += 1
        self._work.put((lambda conn: go(conn), _NullFuture(), _NullLoop()))
        done.wait(30)
        if box[1] is not None:
            raise box[1]
        return box[0]

    def close(self) -> None:
        self.closed = True
        self._work.put(None)
        self._thread.join(timeout=10)
        # anything enqueued between the flag and the join (TOCTOU with
        # run()) fails loudly instead of hanging its awaiter
        self._fail_pending()


class _NullFuture:
    def cancelled(self) -> bool:
        return True

    def done(self) -> bool:
        return True


class _NullLoop:
    def call_soon_threadsafe(self, *a, **k) -> None:
        pass


# ---------------------------------------------------------------------------
# Migrations (alembic replacement: ordered, versioned, idempotent)
# ---------------------------------------------------------------------------

Migration = Tuple[int, str, Callable[[sqlite3.Connection], None]]
_MIGRATIONS: List[Migration] = []


def migration(version: int, description: str):
    """Register a schema migration (runs once, in version order)."""

    def deco(fn: Callable[[sqlite3.Connection], None]):
        _MIGRATIONS.append((version, description, fn))
        return fn

    return deco


@migration(1, "rename reserved-word table user -> users")
def _migrate_user_table(conn: sqlite3.Connection) -> None:
    # ``user`` is a PostgreSQL reserved word; the table kind is now
    # "users". The existence probe is sqlite_master-based because
    # migrations only ever run against the embedded sqlite store —
    # external PG/MySQL deployments are born with the new name.
    def table_exists(name: str) -> bool:
        return conn.execute(
            "SELECT name FROM sqlite_master "
            "WHERE type='table' AND name=?", (name,)
        ).fetchone() is not None

    if not table_exists("user"):
        return
    if table_exists("users"):
        # ``users`` already exists (a CLI path ran create_all_tables
        # before migrations and may even have inserted an admin).
        # Reconcile WITHOUT losing accounts: same-username rows in
        # ``users`` win (newer writes); other old rows keep their id
        # when it's free, else re-insert under a fresh id (logged —
        # records referencing the old id, e.g. api keys, need the
        # operator's attention). Both tables share the generated column
        # order (id, data, created_at, updated_at, username).
        conn.execute(
            "INSERT INTO users SELECT * FROM user WHERE "
            "id NOT IN (SELECT id FROM users) AND "
            "username NOT IN (SELECT username FROM users)"
        )
        remapped = conn.execute(
            "SELECT id, username FROM user WHERE "
            "id IN (SELECT id FROM users) AND "
            "username NOT IN (SELECT username FROM users)"
        ).fetchall()
        if remapped:
            conn.execute(
                "INSERT INTO users (data, created_at, updated_at, "
                "username) SELECT data, created_at, updated_at, "
                "username FROM user WHERE "
                "id IN (SELECT id FROM users) AND "
                "username NOT IN (SELECT username FROM users)"
            )
            logger.warning(
                "user->users migration re-inserted %d user(s) under "
                "fresh ids (old id taken): %s — records referencing "
                "the old user id must be reviewed",
                len(remapped),
                ", ".join(f"{r[1]} (was id {r[0]})" for r in remapped),
            )
        conn.execute("DROP TABLE user")
    else:
        conn.execute("ALTER TABLE user RENAME TO users")
    conn.execute("DROP INDEX IF EXISTS idx_user_username")
    conn.execute(
        "CREATE INDEX IF NOT EXISTS idx_users_username "
        "ON users (username)"
    )


@migration(2, "leadership lease row gains a fencing epoch column")
def _migrate_leadership_epoch(conn: sqlite3.Connection) -> None:
    # pre-PR-10 HA deployments created ``leadership (id, holder,
    # expires_at)`` lazily in the coordinator; the fencing layer needs
    # the monotonic epoch on that row. sqlite_master probe for the same
    # reason as migration 1: migrations only run against the embedded
    # sqlite store.
    row = conn.execute(
        "SELECT name FROM sqlite_master "
        "WHERE type='table' AND name='leadership'"
    ).fetchone()
    if row is None:
        return  # fresh DB: the coordinator creates the new shape
    # column probe via cursor description (PRAGMA table_info would
    # trip the dialect-conformance statement trace)
    cur = conn.execute("SELECT * FROM leadership LIMIT 0")
    cols = {d[0] for d in cur.description}
    if "epoch" not in cols:
        conn.execute(
            "ALTER TABLE leadership ADD COLUMN epoch INTEGER DEFAULT 0"
        )


@migration(3, "model_usage rows gain a tenant index column")
def _migrate_model_usage_tenant(conn: sqlite3.Connection) -> None:
    # the rolling token budget rehydrates from durable usage rows
    # (windowed SUM per tenant — server/tenancy.py durable_budget_
    # spend); pre-ISSUE-15 tables lack the column the index needs.
    # sqlite_master probe for the same reason as migrations 1/2.
    row = conn.execute(
        "SELECT name FROM sqlite_master "
        "WHERE type='table' AND name='model_usage'"
    ).fetchone()
    if row is None:
        return  # fresh DB: create_all_tables builds the new shape
    cur = conn.execute("SELECT * FROM model_usage LIMIT 0")
    cols = {d[0] for d in cur.description}
    if "tenant" not in cols:
        conn.execute(
            "ALTER TABLE model_usage ADD COLUMN tenant TEXT"
        )
    conn.execute(
        "CREATE INDEX IF NOT EXISTS idx_model_usage_tenant "
        "ON model_usage (tenant)"
    )


def run_migrations(db: Database) -> int:
    """Apply pending migrations synchronously (server startup, before the
    event loop). Mirrors the reference's migrate-on-start (reference
    server/server.py:346-369 runs alembic first)."""
    db.execute_sync(
        "CREATE TABLE IF NOT EXISTS schema_version ("
        "version INTEGER PRIMARY KEY, description TEXT, applied_at TEXT)"
    )
    rows = db.execute_sync("SELECT version FROM schema_version")
    applied = {r["version"] for r in rows}
    count = 0
    done = threading.Event()
    err: List[Any] = [None]

    pending = sorted(
        (m for m in _MIGRATIONS if m[0] not in applied), key=lambda m: m[0]
    )

    def go(conn: sqlite3.Connection):
        try:
            for version, desc, fn in pending:
                fn(conn)
                # timestamp computed host-side: datetime('now') is
                # sqlite-only (PG spells it NOW()); a Python value keeps
                # the statement dialect-generic
                import datetime as _dt

                conn.execute(
                    "INSERT INTO schema_version VALUES (?, ?, ?)",
                    (
                        version, desc,
                        _dt.datetime.now(_dt.timezone.utc).isoformat(),
                    ),
                )
                conn.commit()
                logger.info("applied migration %d: %s", version, desc)
        except Exception as e:
            err[0] = e
        finally:
            done.set()

    db._work.put((go, _NullFuture(), _NullLoop()))
    done.wait(60)
    if err[0] is not None:
        raise err[0]
    return len(pending) if not err[0] else count
