"""ActiveRecord base: typed pydantic records with CRUD + post-commit events.

API parity with the reference mixin (reference
gpustack/mixins/active_record.py:510-837): create/get/filter/update/delete,
changed-field diffing, subscribe with heartbeats. Storage is a JSON document
column plus extracted index columns (see orm/__init__ docstring).
"""

from __future__ import annotations

import contextvars
import datetime
import json
import logging
from typing import (
    Any,
    AsyncIterator,
    ClassVar,
    Dict,
    List,
    Optional,
    Tuple,
    Type,
    TypeVar,
)

import pydantic

from gpustack_tpu.orm import changelog, fencing
from gpustack_tpu.orm.db import Database
from gpustack_tpu.server.bus import Event, EventBus, EventType


class ConflictError(Exception):
    """Optimistic-concurrency (CAS) failure: the row changed since this
    snapshot was read. Callers re-fetch and retry (``Record.update``
    does so itself, bounded); the crud route surfaces it as 409."""

    def __init__(self, kind: str, id: int, detail: str = ""):
        self.kind = kind
        self.id = id
        super().__init__(
            f"{kind} id={id} changed concurrently"
            + (f": {detail}" if detail else "")
        )


class StaleEpochError(Exception):
    """Write fenced: it carried a leadership epoch older than the
    current lease — this process was deposed as leader mid-write. The
    write did NOT land. Leader-only loops treat it like any other
    per-iteration failure (the fatal path is already in flight)."""

    def __init__(self, kind: str, id: int, epoch: int, lease_epoch: int):
        self.kind = kind
        self.id = id
        self.epoch = epoch
        self.lease_epoch = lease_epoch
        super().__init__(
            f"{kind} id={id} write fenced: epoch {epoch} < "
            f"current lease epoch {lease_epoch}"
        )

# Per-dialect autoincrement primary key — the single DDL divergence
# across the backends the reference supports (its alembic migrations
# target sqlite/postgres/mysql, gpustack/server/db.py).
PK_CLAUSE = {
    "sqlite": "id INTEGER PRIMARY KEY AUTOINCREMENT",
    "postgres": "id BIGSERIAL PRIMARY KEY",
    "mysql": "id BIGINT PRIMARY KEY AUTO_INCREMENT",
}

logger = logging.getLogger(__name__)

T = TypeVar("T", bound="Record")

_REGISTRY: Dict[str, Type["Record"]] = {}


def register_record(cls: Type[T]) -> Type[T]:
    """Register a Record subclass (table + event kind)."""
    _REGISTRY[cls.__kind__] = cls
    return cls


def registered_records() -> Dict[str, Type["Record"]]:
    return dict(_REGISTRY)


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat()


class Record(pydantic.BaseModel):
    """Base record. Subclasses set ``__kind__`` and optional ``__indexes__``
    (field names extracted into SQL columns for indexed filtering)."""

    # validate_assignment so update(state="error") coerces wire strings
    # back to enum/nested-model types — without it state fields type-drift
    # into raw strings after any HTTP PATCH round-trip.
    model_config = pydantic.ConfigDict(validate_assignment=True)

    __kind__: ClassVar[str] = ""
    __indexes__: ClassVar[Tuple[str, ...]] = ()

    id: int = 0
    created_at: str = ""
    updated_at: str = ""

    # CAS basis: ``updated_at`` as this snapshot was LOADED (set by
    # _from_row/create/save/refresh) — deliberately distinct from the
    # field, which a caller may legitimately rewrite (backdating a
    # timestamp must not defeat, or falsely trip, the concurrency
    # guard). None = never loaded → unconditional write.
    _cas_basis: Optional[str] = pydantic.PrivateAttr(default=None)

    # ---- binding --------------------------------------------------------
    # Process-global by default (one server per process in production).
    # The in-process multi-server chaos harness boots N Servers in ONE
    # process sharing one DB file — each server's task tree (and, via
    # an app middleware, each request handler) additionally carries a
    # context-local binding so server A's controllers publish to A's
    # bus, not whichever server bound last. ``bind`` keeps its global
    # last-wins semantics untouched; ``bind_context`` is the opt-in
    # context layer.

    _db: ClassVar[Optional[Database]] = None
    _bus: ClassVar[Optional[EventBus]] = None

    _binding_ctx: ClassVar[
        "contextvars.ContextVar[Optional[Tuple[Database, EventBus]]]"
    ] = contextvars.ContextVar("record_binding", default=None)

    @classmethod
    def bind(cls, db: Database, bus: EventBus) -> None:
        """Bind the shared database + bus (server startup / test setup)."""
        Record._db = db
        Record._bus = bus

    @classmethod
    def bind_context(cls, db: Database, bus: EventBus) -> None:
        """Bind for THIS context and every task it spawns (HA servers
        sharing a process). Falls back to the global binding wherever
        unset."""
        Record._binding_ctx.set((db, bus))

    @classmethod
    def _binding(cls) -> Tuple[Optional[Database], Optional[EventBus]]:
        ctx = Record._binding_ctx.get()
        if ctx is not None:
            return ctx
        return Record._db, Record._bus

    @classmethod
    def db(cls) -> Database:
        db, _bus = cls._binding()
        assert db is not None, "Record.bind() not called"
        return db

    @classmethod
    def bus(cls) -> EventBus:
        _db, bus = cls._binding()
        assert bus is not None, "Record.bind() not called"
        return bus

    # ---- schema ---------------------------------------------------------
    # The autoincrement primary key is the ONE piece of DDL that differs
    # across the dialects the reference supports (gpustack/server/db.py:
    # sqlite/postgres/mysql); everything else this ORM emits is
    # driver-generic SQL — mechanically enforced by
    # tests/orm/test_dialect_conformance.py, which traces every statement
    # the control plane issues and rejects dialect-specific constructs.

    @classmethod
    def _create_table_sql(cls, dialect: str = "sqlite") -> List[str]:
        cols = ", ".join(
            f"{f} TEXT" for f in cls.__indexes__
        )
        cols = (", " + cols) if cols else ""
        stmts = [
            f"CREATE TABLE IF NOT EXISTS {cls.__kind__} ("
            f"{PK_CLAUSE[dialect]}, data TEXT NOT NULL, "
            f"created_at TEXT, updated_at TEXT{cols})"
        ]
        for f in cls.__indexes__:
            stmts.append(
                f"CREATE INDEX IF NOT EXISTS idx_{cls.__kind__}_{f} "
                f"ON {cls.__kind__} ({f})"
            )
        return stmts

    @classmethod
    def create_all_tables(cls, db: Database) -> None:
        for rec_cls in _REGISTRY.values():
            for stmt in rec_cls._create_table_sql():
                db.execute_sync(stmt)

    # ---- serialization --------------------------------------------------

    def _index_values(self) -> List[Any]:
        vals = []
        for f in self.__indexes__:
            v = getattr(self, f)
            if isinstance(v, (dict, list)):
                v = json.dumps(v)
            elif v is not None and not isinstance(v, (str, int, float)):
                v = str(v)
            vals.append(v)
        return vals

    @classmethod
    def _from_row(cls: Type[T], row) -> T:
        obj = cls.model_validate_json(row["data"])
        obj.id = row["id"]
        obj._cas_basis = obj.updated_at
        return obj

    # ---- CRUD -----------------------------------------------------------

    # ---- fencing plumbing (orm/fencing.py) -----------------------------
    # When the calling context carries a leadership epoch, every write
    # statement appends the fence-guard clause so a deposed leader's
    # write rejects ATOMICALLY; the epoch check and the write are one
    # statement, leaving no check-then-act window. The helpers below run
    # on the DB thread, inside the statement's implicit transaction, so
    # the lease epoch they read is exactly what the guard judged.

    @staticmethod
    def _lease_epoch(conn) -> int:
        row = conn.execute(
            "SELECT epoch FROM leadership WHERE id = 1"
        ).fetchone()
        if row is None:
            return 0
        return int(row["epoch"] or 0)

    @classmethod
    def _audit_fenced(
        cls, conn, record_id: int, epoch: int, landed: bool
    ) -> int:
        """Report one fenced-write attempt to the audit tap; returns
        the lease epoch observed in this transaction. Callers skip this
        (and its SELECT) for landed writes when no audit tap is set —
        the lease epoch is only NEEDED to classify a rejected write."""
        lease = cls._lease_epoch(conn)
        hook = fencing.audit_hook
        if hook is not None:
            try:
                hook(cls.__kind__, record_id, epoch, lease, landed)
            except Exception:  # noqa: BLE001 — taps never break writes
                logger.exception("fencing audit hook failed")
        return lease

    @classmethod
    def _guarded_execute(cls, conn, sql, params, epoch, record_id):
        """Execute one (possibly fence-guarded) write on the DB
        thread; returns (cursor, landed, lease_epoch). One home for
        the guard protocol create/save/set_field/delete share — the
        lease epoch is read (same transaction) only when NEEDED: the
        write was rejected, or the lossless audit tap is attached."""
        cur = conn.execute(sql, params)
        landed = cur.rowcount != 0
        lease = 0
        if epoch is not None and (
            not landed or fencing.audit_hook is not None
        ):
            lease = cls._audit_fenced(conn, record_id, epoch, landed)
        return cur, landed, lease

    @classmethod
    def _raise_fenced(cls, record_id, epoch, lease):
        fencing.record_fenced(cls.__kind__)
        raise StaleEpochError(cls.__kind__, record_id, epoch, lease)

    @classmethod
    def _append_change(
        cls, conn, db, event_type: str, record_id: int,
        changes_json=None,
    ) -> None:
        """Transactional replication (orm/changelog.py): when this
        binding carries an HA origin identity, the change-log entry
        commits WITH the data write — a SIGKILL between them is
        impossible, which kills the PR 10 unflushed-outbox crash
        window. Runs on the DB thread inside the write's open
        transaction; a failure here rolls the data write back (the
        caller's except path), never half-lands it."""
        origin = getattr(db, "changelog_origin", "")
        if origin:
            changelog.append_change(
                conn, origin, cls.__kind__, event_type, record_id,
                changes_json,
            )

    @classmethod
    async def create(cls: Type[T], obj: T) -> T:
        obj.created_at = obj.created_at or _now()
        obj.updated_at = _now()
        idx_cols = "".join(f", {f}" for f in cls.__indexes__)
        data = obj.model_dump_json(exclude={"id"})
        params = [data, obj.created_at, obj.updated_at] + obj._index_values()
        epoch = fencing.fence_epoch()
        db = cls.db()
        if epoch is None:
            idx_q = ", ?" * len(cls.__indexes__)
            sql = (
                f"INSERT INTO {cls.__kind__} "
                f"(data, created_at, updated_at{idx_cols}) "
                f"VALUES (?, ?, ?{idx_q})"
            )
        else:
            # guarded insert: INSERT ... SELECT so the fence clause can
            # gate row production itself (VALUES admits no WHERE)
            marks = ", ".join(["?"] * (3 + len(cls.__indexes__)))
            sql = (
                f"INSERT INTO {cls.__kind__} "
                f"(data, created_at, updated_at{idx_cols}) "
                f"SELECT {marks}{db.dual_from()} "
                f"WHERE {db.fence_guard()}"
            )
            params = params + [epoch]

        def go(conn):
            try:
                cur, landed, lease = cls._guarded_execute(
                    conn, sql, params, epoch, 0
                )
                rowid = cur.lastrowid
                if landed:
                    cls._append_change(conn, db, "CREATED", rowid)
                conn.commit()
            except BaseException:
                conn.rollback()
                raise
            if not landed:
                return ("fenced", lease)
            return ("ok", rowid)

        outcome, value = await db.run(go)
        if outcome == "fenced":
            cls._raise_fenced(0, epoch, value)
        obj.id = value
        obj._cas_basis = obj.updated_at
        cls.bus().publish(
            Event(
                kind=cls.__kind__,
                type=EventType.CREATED,
                id=obj.id,
                data=obj.model_dump(mode="json"),
            )
        )
        return obj

    @classmethod
    async def get(cls: Type[T], id: int) -> Optional[T]:
        rows = await cls.db().execute(
            f"SELECT * FROM {cls.__kind__} WHERE id = ?", (id,)
        )
        return cls._from_row(rows[0]) if rows else None

    @classmethod
    async def filter(
        cls: Type[T],
        limit: Optional[int] = None,
        offset: int = 0,
        order_by: str = "id",
        since_id: Optional[int] = None,
        **conds: Any,
    ) -> List[T]:
        """Filter by equality conditions. Index fields filter in SQL; other
        fields post-filter in Python. ``since_id`` adds ``id > ?`` —
        keyset pagination for full-table readers (client ``list_all``):
        unlike OFFSET, a row deleted between pages cannot shift a live
        row out of the result set."""
        sql_conds = {
            k: v for k, v in conds.items() if k in cls.__indexes__ or k == "id"
        }
        py_conds = {k: v for k, v in conds.items() if k not in sql_conds}
        parts: List[str] = []
        params: List[Any] = []
        for k, v in sql_conds.items():
            if isinstance(v, (dict, list)):
                v = json.dumps(v)
            elif v is not None and not isinstance(v, (str, int, float)):
                v = str(v)
            parts.append(f"{k} = ?")
            params.append(v)
        if since_id is not None:
            parts.append("id > ?")
            params.append(int(since_id))
        where = (" WHERE " + " AND ".join(parts)) if parts else ""
        sql = f"SELECT * FROM {cls.__kind__}{where} ORDER BY {order_by}"
        if limit is not None and not py_conds:
            sql += f" LIMIT {int(limit)} OFFSET {int(offset)}"
        rows = await cls.db().execute(sql, params)
        out = [cls._from_row(r) for r in rows]
        if py_conds:
            def match(o: T) -> bool:
                for k, v in py_conds.items():
                    ov = getattr(o, k)
                    ov = ov.value if hasattr(ov, "value") else ov
                    vv = v.value if hasattr(v, "value") else v
                    if ov != vv:
                        return False
                return True

            out = [o for o in out if match(o)]
            if limit is not None:
                out = out[offset : offset + limit]
        return out

    @classmethod
    async def all(cls: Type[T]) -> List[T]:
        return await cls.filter()

    @classmethod
    async def get_many(cls: Type[T], ids) -> Dict[int, T]:
        """Batch fetch by primary key: {id: record} for the ids that
        exist (missing ids are simply absent). One ``IN`` query per
        chunk instead of one round-trip per id — the change-log tailer
        re-fetches whole replication batches through this, so follower
        propagation stays O(queries-per-kind), not O(entries)."""
        wanted = sorted({int(i) for i in ids})
        out: Dict[int, T] = {}
        chunk_size = 500  # stay well under sqlite's host-param limit
        for start in range(0, len(wanted), chunk_size):
            chunk = wanted[start:start + chunk_size]
            marks = ", ".join("?" * len(chunk))
            rows = await cls.db().execute(
                f"SELECT * FROM {cls.__kind__} WHERE id IN ({marks})",
                chunk,
            )
            for row in rows:
                obj = cls._from_row(row)
                out[obj.id] = obj
        return out

    @classmethod
    async def filter_created_before(
        cls: Type[T], cutoff_iso: str, limit: Optional[int] = None
    ) -> List[T]:
        """Rows with created_at < cutoff — an indexed-range SQL query
        (archival sweeps must not materialize the whole hot table)."""
        sql = (
            f"SELECT * FROM {cls.__kind__} WHERE created_at < ? "
            f"ORDER BY id"
        )
        if limit is not None:
            sql += f" LIMIT {int(limit)}"
        rows = await cls.db().execute(sql, [cutoff_iso])
        return [cls._from_row(r) for r in rows]

    @classmethod
    async def filter_created_after(
        cls: Type[T], cutoff_iso: str, limit: Optional[int] = None,
        newest_first: bool = False,
    ) -> List[T]:
        """Rows with created_at >= cutoff, oldest first (dashboard
        time-series reads). ``newest_first`` flips the order so a LIMIT
        keeps the most RECENT rows of a large window."""
        order = "DESC" if newest_first else "ASC"
        sql = (
            f"SELECT * FROM {cls.__kind__} WHERE created_at >= ? "
            f"ORDER BY created_at {order}"
        )
        if limit is not None:
            sql += f" LIMIT {int(limit)}"
        rows = await cls.db().execute(sql, [cutoff_iso])
        return [cls._from_row(r) for r in rows]

    @classmethod
    async def first(cls: Type[T], **conds: Any) -> Optional[T]:
        items = await cls.filter(limit=1, **conds)
        return items[0] if items else None

    @classmethod
    async def count(cls: Type[T], **conds: Any) -> int:
        return len(await cls.filter(**conds))

    async def refresh(self: T) -> Optional[T]:
        fresh = await type(self).get(self.id)
        if fresh is not None:
            for f in type(self).model_fields:
                setattr(self, f, getattr(fresh, f))
            self._cas_basis = fresh._cas_basis
        return fresh

    @classmethod
    async def set_field(cls, id: int, field: str, value: Any) -> int:
        """Column-targeted single-field JSON write. Unlike
        :meth:`update`, this does NOT persist the whole document, so a
        stale in-memory snapshot can never revert concurrent writers'
        other fields — for hot-path server-internal markers (e.g. the
        autoscaler wake marker) written without a re-fetch/409 dance.
        Deliberately bypasses the event bus (no watch event) but DOES
        bump ``updated_at``: the CAS guard on whole-document saves
        keys on it, and an invisible set_field would let a concurrent
        save's CAS pass and silently revert this very write — the
        hazard set_field exists to avoid, mirrored. Index columns may
        not be written this way. Returns the affected row count."""
        if field in cls.__indexes__:
            raise ValueError(
                f"{field!r} is an index column; use update()"
            )
        db = cls.db()
        # nested writer: the target field, then the document's own
        # updated_at (kept in lockstep with the SQL column) — bind
        # order is textual: inner value first, then the timestamp
        setter = db.json_set("updated_at", col=db.json_set(field))
        # bind JSON text: every dialect spelling parses it, so numbers
        # stay JSON numbers on sqlite/postgres/mysql alike
        encoded = json.dumps(_jsonable(value))
        now = _now()
        epoch = fencing.fence_epoch()
        sql = (
            f"UPDATE {cls.__kind__} SET data = {setter}, "
            "updated_at = ? WHERE id = ?"
        )
        params: List[Any] = [encoded, json.dumps(now), now, id]
        if epoch is not None:
            sql += f" AND {db.fence_guard()}"
            params.append(epoch)

        def go(conn):
            cur, landed, lease = cls._guarded_execute(
                conn, sql, params, epoch, id
            )
            conn.commit()
            if not landed and epoch is not None and lease > epoch:
                return ("fenced", lease)
            return ("ok", cur.rowcount)

        outcome, count = await db.run(go)
        if outcome == "fenced":
            cls._raise_fenced(id, epoch, count)
        return count

    async def update(
        self: T, _retries: int = 3, **fields: Any
    ) -> T:
        """Apply field updates, persist, publish UPDATED with a
        changed-field diff (old, new) — reference active_record.py:46-74.

        Persistence is CAS-guarded (see :meth:`save`); on
        :class:`ConflictError` the row is re-fetched and the SAME field
        updates re-applied, up to ``_retries`` times, so convergence
        loops keep their fire-and-forget ergonomics while a concurrent
        writer's OTHER fields can never be silently reverted by this
        stale snapshot (the pre-CAS lost-update window). ``_retries=0``
        surfaces the conflict to the caller (the crud route's 409
        path)."""
        attempt = 0
        while True:
            changes: Dict[str, Any] = {}
            for k, v in fields.items():
                old = getattr(self, k)
                if old != v:
                    old_j = old.value if hasattr(old, "value") else old
                    new_j = v.value if hasattr(v, "value") else v
                    changes[k] = (_jsonable(old_j), _jsonable(new_j))
                setattr(self, k, v)
            if not changes:
                return self
            try:
                await self.save(changes=changes)
                return self
            except ConflictError:
                if attempt >= _retries:
                    raise
                attempt += 1
                fresh = await type(self).get(self.id)
                if fresh is None:
                    raise KeyError(
                        f"{type(self).__kind__} id={self.id} "
                        "does not exist"
                    )
                for f in type(self).model_fields:
                    setattr(self, f, getattr(fresh, f))
                self._cas_basis = fresh._cas_basis

    async def save(self: T, changes: Optional[Dict[str, Any]] = None) -> T:
        """Persist the whole document with optimistic concurrency: the
        UPDATE is conditioned on ``updated_at`` still matching the value
        this snapshot was loaded with (rowcount 0 → typed
        :class:`ConflictError`; callers re-fetch and retry bounded —
        :meth:`update` does it for them). This closes the residual
        lost-update windows the per-site re-fetch guards (crud 409
        path, autoscaler, rollout ``_record``) each narrowed but could
        not eliminate: the guard and the write are one statement.
        Fenced contexts additionally carry the leadership-epoch guard
        (see orm/fencing.py)."""
        expected = self._cas_basis
        prior_field = self.updated_at
        self.updated_at = _now()
        cls = type(self)
        epoch = fencing.fence_epoch()
        db = cls.db()
        idx_sets = "".join(f", {f} = ?" for f in cls.__indexes__)
        data = self.model_dump_json(exclude={"id"})
        # created_at is both a document field and a real SQL column (range
        # queries index it); keep the column in sync on every save
        params = (
            [data, self.updated_at, self.created_at]
            + self._index_values()
            + [self.id]
        )
        where = "WHERE id = ?"
        if expected:
            # CAS on the loaded snapshot; a legacy row saved without
            # ever being loaded (empty updated_at) falls back to the
            # unconditional write
            where += " AND updated_at = ?"
            params = params + [expected]
        if epoch is not None:
            where += f" AND {db.fence_guard()}"
            params = params + [epoch]

        # replication diff encoded once, off the DB thread; only
        # needed when this binding replicates at all
        changes_json = (
            changelog.encode_changes(changes)
            if getattr(db, "changelog_origin", "") else None
        )

        def go(conn):
            try:
                cur, landed, lease = cls._guarded_execute(
                    conn,
                    f"UPDATE {cls.__kind__} SET data = ?, "
                    f"updated_at = ?, created_at = ?{idx_sets} {where}",
                    params, epoch, self.id,
                )
                if landed:
                    cls._append_change(
                        conn, db, "UPDATED", self.id, changes_json
                    )
                    conn.commit()
                    return ("ok", cur.rowcount)
                if epoch is not None and lease > epoch:
                    conn.commit()
                    return ("fenced", lease)
                row = conn.execute(
                    f"SELECT updated_at FROM {cls.__kind__} "
                    "WHERE id = ?",
                    (self.id,),
                ).fetchone()
                conn.commit()
            except BaseException:
                conn.rollback()
                raise
            if row is None:
                return ("missing", None)
            return ("conflict", row["updated_at"])

        outcome, value = await db.run(go)
        if outcome == "ok":
            self._cas_basis = self.updated_at
        else:
            # the write did not land: restore the field so a caller
            # retry sees the object exactly as before the attempt
            self.updated_at = prior_field
        if outcome == "fenced":
            type(self)._raise_fenced(self.id, epoch, value)
        if outcome == "missing":
            raise KeyError(f"{cls.__kind__} id={self.id} does not exist")
        if outcome == "conflict":
            raise ConflictError(
                cls.__kind__, self.id,
                f"updated_at moved {expected!r} -> {value!r}",
            )
        cls.bus().publish(
            Event(
                kind=cls.__kind__,
                type=EventType.UPDATED,
                id=self.id,
                data=self.model_dump(mode="json"),
                changes=changes,
            )
        )
        return self

    async def delete(self) -> None:
        cls = type(self)
        epoch = fencing.fence_epoch()
        db = cls.db()
        sql = f"DELETE FROM {cls.__kind__} WHERE id = ?"
        params: List[Any] = [self.id]
        if epoch is not None:
            sql += f" AND {db.fence_guard()}"
            params.append(epoch)

        def go(conn):
            try:
                cur, landed, lease = cls._guarded_execute(
                    conn, sql, params, epoch, self.id
                )
                if landed and cur.rowcount:
                    cls._append_change(conn, db, "DELETED", self.id)
                conn.commit()
            except BaseException:
                conn.rollback()
                raise
            if not landed and epoch is not None and lease > epoch:
                return ("fenced", lease)
            return ("ok", cur.rowcount)

        outcome, count = await db.run(go)
        if outcome == "fenced":
            cls._raise_fenced(self.id, epoch, count)
        if count:
            cls.bus().publish(
                Event(
                    kind=cls.__kind__,
                    type=EventType.DELETED,
                    id=self.id,
                    data=self.model_dump(mode="json"),
                )
            )

    # ---- watch ----------------------------------------------------------

    @classmethod
    async def subscribe(
        cls: Type[T],
        send_initial: bool = True,
        heartbeat: float = 15.0,
    ) -> AsyncIterator[Event]:
        """Async stream of events for this kind. With ``send_initial``,
        existing rows are replayed as synthetic CREATED events first
        (informer-style list+watch); a RESYNC event means the consumer
        must re-list. HEARTBEAT every ``heartbeat`` seconds of silence
        (reference active_record.py:789-837)."""
        sub = cls.bus().subscribe(kinds={cls.__kind__})
        try:
            if send_initial:
                for obj in await cls.all():
                    yield Event(
                        kind=cls.__kind__,
                        type=EventType.CREATED,
                        id=obj.id,
                        data=obj.model_dump(mode="json"),
                    )
            while True:
                yield await sub.get(timeout=heartbeat)
        finally:
            sub.close()


def _jsonable(v: Any) -> Any:
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return str(v)
