"""ActiveRecord base: typed pydantic records with CRUD + post-commit events.

API parity with the reference mixin (reference
gpustack/mixins/active_record.py:510-837): create/get/filter/update/delete,
changed-field diffing, subscribe with heartbeats. Storage is a JSON document
column plus extracted index columns (see orm/__init__ docstring).
"""

from __future__ import annotations

import datetime
import json
import logging
from typing import (
    Any,
    AsyncIterator,
    ClassVar,
    Dict,
    List,
    Optional,
    Tuple,
    Type,
    TypeVar,
)

import pydantic

from gpustack_tpu.orm.db import Database
from gpustack_tpu.server.bus import Event, EventBus, EventType

# Per-dialect autoincrement primary key — the single DDL divergence
# across the backends the reference supports (its alembic migrations
# target sqlite/postgres/mysql, gpustack/server/db.py).
PK_CLAUSE = {
    "sqlite": "id INTEGER PRIMARY KEY AUTOINCREMENT",
    "postgres": "id BIGSERIAL PRIMARY KEY",
    "mysql": "id BIGINT PRIMARY KEY AUTO_INCREMENT",
}

logger = logging.getLogger(__name__)

T = TypeVar("T", bound="Record")

_REGISTRY: Dict[str, Type["Record"]] = {}


def register_record(cls: Type[T]) -> Type[T]:
    """Register a Record subclass (table + event kind)."""
    _REGISTRY[cls.__kind__] = cls
    return cls


def registered_records() -> Dict[str, Type["Record"]]:
    return dict(_REGISTRY)


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat()


class Record(pydantic.BaseModel):
    """Base record. Subclasses set ``__kind__`` and optional ``__indexes__``
    (field names extracted into SQL columns for indexed filtering)."""

    # validate_assignment so update(state="error") coerces wire strings
    # back to enum/nested-model types — without it state fields type-drift
    # into raw strings after any HTTP PATCH round-trip.
    model_config = pydantic.ConfigDict(validate_assignment=True)

    __kind__: ClassVar[str] = ""
    __indexes__: ClassVar[Tuple[str, ...]] = ()

    id: int = 0
    created_at: str = ""
    updated_at: str = ""

    # ---- binding --------------------------------------------------------

    _db: ClassVar[Optional[Database]] = None
    _bus: ClassVar[Optional[EventBus]] = None

    @classmethod
    def bind(cls, db: Database, bus: EventBus) -> None:
        """Bind the shared database + bus (server startup / test setup)."""
        Record._db = db
        Record._bus = bus

    @classmethod
    def db(cls) -> Database:
        assert Record._db is not None, "Record.bind() not called"
        return Record._db

    @classmethod
    def bus(cls) -> EventBus:
        assert Record._bus is not None, "Record.bind() not called"
        return Record._bus

    # ---- schema ---------------------------------------------------------
    # The autoincrement primary key is the ONE piece of DDL that differs
    # across the dialects the reference supports (gpustack/server/db.py:
    # sqlite/postgres/mysql); everything else this ORM emits is
    # driver-generic SQL — mechanically enforced by
    # tests/orm/test_dialect_conformance.py, which traces every statement
    # the control plane issues and rejects dialect-specific constructs.

    @classmethod
    def _create_table_sql(cls, dialect: str = "sqlite") -> List[str]:
        cols = ", ".join(
            f"{f} TEXT" for f in cls.__indexes__
        )
        cols = (", " + cols) if cols else ""
        stmts = [
            f"CREATE TABLE IF NOT EXISTS {cls.__kind__} ("
            f"{PK_CLAUSE[dialect]}, data TEXT NOT NULL, "
            f"created_at TEXT, updated_at TEXT{cols})"
        ]
        for f in cls.__indexes__:
            stmts.append(
                f"CREATE INDEX IF NOT EXISTS idx_{cls.__kind__}_{f} "
                f"ON {cls.__kind__} ({f})"
            )
        return stmts

    @classmethod
    def create_all_tables(cls, db: Database) -> None:
        for rec_cls in _REGISTRY.values():
            for stmt in rec_cls._create_table_sql():
                db.execute_sync(stmt)

    # ---- serialization --------------------------------------------------

    def _index_values(self) -> List[Any]:
        vals = []
        for f in self.__indexes__:
            v = getattr(self, f)
            if isinstance(v, (dict, list)):
                v = json.dumps(v)
            elif v is not None and not isinstance(v, (str, int, float)):
                v = str(v)
            vals.append(v)
        return vals

    @classmethod
    def _from_row(cls: Type[T], row) -> T:
        obj = cls.model_validate_json(row["data"])
        obj.id = row["id"]
        return obj

    # ---- CRUD -----------------------------------------------------------

    @classmethod
    async def create(cls: Type[T], obj: T) -> T:
        obj.created_at = obj.created_at or _now()
        obj.updated_at = _now()
        idx_cols = "".join(f", {f}" for f in cls.__indexes__)
        idx_q = ", ?" * len(cls.__indexes__)
        data = obj.model_dump_json(exclude={"id"})
        params = [data, obj.created_at, obj.updated_at] + obj._index_values()

        def go(conn):
            cur = conn.execute(
                f"INSERT INTO {cls.__kind__} "
                f"(data, created_at, updated_at{idx_cols}) "
                f"VALUES (?, ?, ?{idx_q})",
                params,
            )
            conn.commit()
            return cur.lastrowid

        obj.id = await cls.db().run(go)
        cls.bus().publish(
            Event(
                kind=cls.__kind__,
                type=EventType.CREATED,
                id=obj.id,
                data=obj.model_dump(mode="json"),
            )
        )
        return obj

    @classmethod
    async def get(cls: Type[T], id: int) -> Optional[T]:
        rows = await cls.db().execute(
            f"SELECT * FROM {cls.__kind__} WHERE id = ?", (id,)
        )
        return cls._from_row(rows[0]) if rows else None

    @classmethod
    async def filter(
        cls: Type[T],
        limit: Optional[int] = None,
        offset: int = 0,
        order_by: str = "id",
        **conds: Any,
    ) -> List[T]:
        """Filter by equality conditions. Index fields filter in SQL; other
        fields post-filter in Python."""
        sql_conds = {
            k: v for k, v in conds.items() if k in cls.__indexes__ or k == "id"
        }
        py_conds = {k: v for k, v in conds.items() if k not in sql_conds}
        where = ""
        params: List[Any] = []
        if sql_conds:
            parts = []
            for k, v in sql_conds.items():
                if isinstance(v, (dict, list)):
                    v = json.dumps(v)
                elif v is not None and not isinstance(v, (str, int, float)):
                    v = str(v)
                parts.append(f"{k} = ?")
                params.append(v)
            where = " WHERE " + " AND ".join(parts)
        sql = f"SELECT * FROM {cls.__kind__}{where} ORDER BY {order_by}"
        if limit is not None and not py_conds:
            sql += f" LIMIT {int(limit)} OFFSET {int(offset)}"
        rows = await cls.db().execute(sql, params)
        out = [cls._from_row(r) for r in rows]
        if py_conds:
            def match(o: T) -> bool:
                for k, v in py_conds.items():
                    ov = getattr(o, k)
                    ov = ov.value if hasattr(ov, "value") else ov
                    vv = v.value if hasattr(v, "value") else v
                    if ov != vv:
                        return False
                return True

            out = [o for o in out if match(o)]
            if limit is not None:
                out = out[offset : offset + limit]
        return out

    @classmethod
    async def all(cls: Type[T]) -> List[T]:
        return await cls.filter()

    @classmethod
    async def filter_created_before(
        cls: Type[T], cutoff_iso: str, limit: Optional[int] = None
    ) -> List[T]:
        """Rows with created_at < cutoff — an indexed-range SQL query
        (archival sweeps must not materialize the whole hot table)."""
        sql = (
            f"SELECT * FROM {cls.__kind__} WHERE created_at < ? "
            f"ORDER BY id"
        )
        if limit is not None:
            sql += f" LIMIT {int(limit)}"
        rows = await cls.db().execute(sql, [cutoff_iso])
        return [cls._from_row(r) for r in rows]

    @classmethod
    async def filter_created_after(
        cls: Type[T], cutoff_iso: str, limit: Optional[int] = None,
        newest_first: bool = False,
    ) -> List[T]:
        """Rows with created_at >= cutoff, oldest first (dashboard
        time-series reads). ``newest_first`` flips the order so a LIMIT
        keeps the most RECENT rows of a large window."""
        order = "DESC" if newest_first else "ASC"
        sql = (
            f"SELECT * FROM {cls.__kind__} WHERE created_at >= ? "
            f"ORDER BY created_at {order}"
        )
        if limit is not None:
            sql += f" LIMIT {int(limit)}"
        rows = await cls.db().execute(sql, [cutoff_iso])
        return [cls._from_row(r) for r in rows]

    @classmethod
    async def first(cls: Type[T], **conds: Any) -> Optional[T]:
        items = await cls.filter(limit=1, **conds)
        return items[0] if items else None

    @classmethod
    async def count(cls: Type[T], **conds: Any) -> int:
        return len(await cls.filter(**conds))

    async def refresh(self: T) -> Optional[T]:
        fresh = await type(self).get(self.id)
        if fresh is not None:
            for f in type(self).model_fields:
                setattr(self, f, getattr(fresh, f))
        return fresh

    @classmethod
    async def set_field(cls, id: int, field: str, value: Any) -> int:
        """Column-targeted single-field JSON write. Unlike
        :meth:`update`, this does NOT persist the whole document, so a
        stale in-memory snapshot can never revert concurrent writers'
        other fields — for hot-path server-internal markers (e.g. the
        autoscaler wake marker) written without a re-fetch/409 dance.
        Deliberately bypasses the event bus (no watch event, no
        updated_at bump); index columns may not be written this way.
        Returns the affected row count."""
        if field in cls.__indexes__:
            raise ValueError(
                f"{field!r} is an index column; use update()"
            )
        setter = cls.db().json_set(field)
        # bind JSON text: every dialect spelling parses it, so numbers
        # stay JSON numbers on sqlite/postgres/mysql alike
        encoded = json.dumps(_jsonable(value))

        def go(conn):
            cur = conn.execute(
                f"UPDATE {cls.__kind__} SET data = {setter} "
                "WHERE id = ?",
                (encoded, id),
            )
            conn.commit()
            return cur.rowcount

        return await cls.db().run(go)

    async def update(self: T, **fields: Any) -> T:
        """Apply field updates, persist, publish UPDATED with a
        changed-field diff (old, new) — reference active_record.py:46-74."""
        changes: Dict[str, Any] = {}
        for k, v in fields.items():
            old = getattr(self, k)
            if old != v:
                old_j = old.value if hasattr(old, "value") else old
                new_j = v.value if hasattr(v, "value") else v
                changes[k] = (_jsonable(old_j), _jsonable(new_j))
            setattr(self, k, v)
        if not changes:
            return self
        await self.save(changes=changes)
        return self

    async def save(self: T, changes: Optional[Dict[str, Any]] = None) -> T:
        self.updated_at = _now()
        cls = type(self)
        idx_sets = "".join(f", {f} = ?" for f in cls.__indexes__)
        data = self.model_dump_json(exclude={"id"})
        # created_at is both a document field and a real SQL column (range
        # queries index it); keep the column in sync on every save
        params = (
            [data, self.updated_at, self.created_at]
            + self._index_values()
            + [self.id]
        )

        def go(conn):
            cur = conn.execute(
                f"UPDATE {cls.__kind__} SET data = ?, updated_at = ?, "
                f"created_at = ?{idx_sets} WHERE id = ?",
                params,
            )
            conn.commit()
            return cur.rowcount

        count = await cls.db().run(go)
        if count == 0:
            raise KeyError(f"{cls.__kind__} id={self.id} does not exist")
        cls.bus().publish(
            Event(
                kind=cls.__kind__,
                type=EventType.UPDATED,
                id=self.id,
                data=self.model_dump(mode="json"),
                changes=changes,
            )
        )
        return self

    async def delete(self) -> None:
        cls = type(self)

        def go(conn):
            cur = conn.execute(
                f"DELETE FROM {cls.__kind__} WHERE id = ?", (self.id,)
            )
            conn.commit()
            return cur.rowcount

        count = await cls.db().run(go)
        if count:
            cls.bus().publish(
                Event(
                    kind=cls.__kind__,
                    type=EventType.DELETED,
                    id=self.id,
                    data=self.model_dump(mode="json"),
                )
            )

    # ---- watch ----------------------------------------------------------

    @classmethod
    async def subscribe(
        cls: Type[T],
        send_initial: bool = True,
        heartbeat: float = 15.0,
    ) -> AsyncIterator[Event]:
        """Async stream of events for this kind. With ``send_initial``,
        existing rows are replayed as synthetic CREATED events first
        (informer-style list+watch); a RESYNC event means the consumer
        must re-list. HEARTBEAT every ``heartbeat`` seconds of silence
        (reference active_record.py:789-837)."""
        sub = cls.bus().subscribe(kinds={cls.__kind__})
        try:
            if send_initial:
                for obj in await cls.all():
                    yield Event(
                        kind=cls.__kind__,
                        type=EventType.CREATED,
                        id=obj.id,
                        data=obj.model_dump(mode="json"),
                    )
            while True:
                yield await sub.get(timeout=heartbeat)
        finally:
            sub.close()


def _jsonable(v: Any) -> Any:
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return str(v)
