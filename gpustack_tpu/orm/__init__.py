"""Lightweight async ORM over sqlite (stdlib) with ActiveRecord semantics.

The reference builds on SQLModel/SQLAlchemy with an ActiveRecord mixin that
publishes a bus event after every commit (reference
gpustack/mixins/active_record.py:78-92) — neither SQLAlchemy nor SQLModel
exists in this image, and a cluster-manager appliance doesn't need a full
RDBMS abstraction. This ORM keeps the *semantics* that matter:

- async CRUD (``create/get/filter/update/delete``) on typed pydantic records
- changed-field diffing on update (reference active_record.py:46-74)
- post-commit event publication into the EventBus
- watch streams (``subscribe``) with heartbeats for HTTP watchers

Storage model: one sqlite table per record type with a JSON document column
plus extracted index columns — document-store reads, SQL-indexed filters.
sqlite runs in WAL mode behind a single writer thread; Postgres can slot in
behind the same interface later (the reference defaults to embedded
Postgres, docs/architecture.md:33).
"""

from gpustack_tpu.orm.db import Database
from gpustack_tpu.orm.record import (
    ConflictError,
    Record,
    StaleEpochError,
    register_record,
)

__all__ = [
    "ConflictError",
    "Database",
    "Record",
    "StaleEpochError",
    "register_record",
]
