"""gpustack_tpu — a TPU-native cluster manager and serving stack for AI models.

A ground-up re-design of the capabilities of gpustack/gpustack (reference:
/root/reference) for Cloud TPU:

- ``models/``    functional JAX transformer families (Llama/Qwen/Mistral dense,
                 Mixtral-class MoE) built for XLA: scan-over-layers, static
                 shapes, bf16 MXU matmuls.
- ``parallel/``  device-mesh construction and sharding policies (dp/sp/ep/tp
                 axes over ICI/DCN) — the TPU replacement for the reference's
                 NCCL rank-table plumbing (see reference
                 gpustack/worker/backends/vllm.py:941-1025).
- ``engine/``    the built-in TPU serving engine (slot-based KV cache,
                 continuous batching, OpenAI HTTP front) — the data plane the
                 reference delegates to vLLM/SGLang containers.
- ``ops/``       Pallas TPU kernels for the hot paths.
- ``schemas/``, ``orm/``, ``server/``, ``scheduler/``, ``policies/``,
  ``routes/``, ``api/``, ``worker/``, ``detectors/``, ``client/`` — the
  control plane (state machine, reconcilers, slice-aware scheduler, worker
  agent, OpenAI gateway), mirroring the reference's layer map (SURVEY.md §1)
  with a TPU-native device model.
"""

__version__ = "0.1.0"
