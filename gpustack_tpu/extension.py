"""Plugin/extension system (reference gpustack/extension.py:57-78).

Plugins extend the server without forking it: mount routers, register
async tasks, supply an HA coordinator. Discovery is module-path based via
``GPUSTACK_TPU_PLUGINS=pkg.mod1,pkg.mod2`` (the reference uses the
``gpustack.plugins`` entry-point group; entry points require installed
distributions, while a module list also covers in-tree/ad-hoc plugins —
both resolve to "import something and find Plugin subclasses").

Each listed module is imported and every ``Plugin`` subclass defined in
it is instantiated once.
"""

from __future__ import annotations

import importlib
import inspect
import logging
import os
from typing import List, Optional

logger = logging.getLogger(__name__)

PLUGINS_ENV = "GPUSTACK_TPU_PLUGINS"


class Plugin:
    """Base class: override any subset of the hooks."""

    name: str = ""

    def setup_app(self, app, cfg) -> None:
        """Mount routes / middlewares on the aiohttp application."""

    def tasks(self, app, cfg) -> List:
        """Coroutines started with the server and cancelled on stop."""
        return []

    def coordinator(self, cfg):
        """Return a Coordinator instance to replace the default, or
        None (reference: plugins supply distributed coordinators,
        server/server.py:1166-1194)."""
        return None


def iter_plugin_classes(spec: Optional[str] = None):
    spec = spec if spec is not None else os.environ.get(PLUGINS_ENV, "")
    for module_path in filter(None, (p.strip() for p in spec.split(","))):
        try:
            module = importlib.import_module(module_path)
        except Exception as e:
            # any import-time failure (not just ImportError): one broken
            # plugin must never abort server startup
            logger.error("plugin module %r failed to import: %s",
                         module_path, e)
            continue
        for _, obj in inspect.getmembers(module, inspect.isclass):
            if (
                issubclass(obj, Plugin)
                and obj is not Plugin
                and obj.__module__ == module.__name__
            ):
                yield obj


def load_plugins(spec: Optional[str] = None) -> List[Plugin]:
    plugins: List[Plugin] = []
    for cls in iter_plugin_classes(spec):
        try:
            plugin = cls()
            plugins.append(plugin)
            logger.info(
                "loaded plugin %s (%s)",
                plugin.name or cls.__name__, cls.__module__,
            )
        except Exception:
            logger.exception("plugin %s failed to initialize", cls)
    return plugins
