// model-meta: checkpoint metadata parser + HBM estimator CLI.
//
// The TPU-native replacement for the reference's gguf-parser Go binary
// (reference gpustack/worker/tools_manager.py:19 downloads it;
// scheduler/calculator.py:550-566 shells out for layer-wise VRAM
// estimates). The scheduler shells out to this tool when a local
// checkpoint directory exists, getting exact tensor sizes instead of
// config-derived estimates.
//
// Supported formats:
//   - safetensors: 8-byte LE header length + JSON header of
//     {name: {dtype, shape, data_offsets}}
//   - gguf (metadata only): magic "GGUF", version, tensor/kv counts and
//     per-tensor dtype/shape records — enough for weight-byte accounting
//
// Usage:
//   model-meta <model_dir | file.safetensors | file.gguf>
//
// Output: one JSON object on stdout:
//   {"format": "...", "files": N, "tensors": N, "total_bytes": N,
//    "params": N, "bytes_by_dtype": {...}, "max_layer_bytes": N}
//
// No third-party deps: the JSON subset emitted by safetensors writers is
// parsed with a small recursive-descent parser below.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <fstream>
#include <map>
#include <regex>
#include <string>
#include <sys/stat.h>
#include <vector>

namespace {

struct TensorInfo {
  std::string name;
  std::string dtype;
  std::vector<int64_t> shape;
  int64_t bytes = 0;
};

int64_t dtype_bits(const std::string &dt) {
  if (dt == "F64" || dt == "I64" || dt == "U64") return 64;
  if (dt == "F32" || dt == "I32" || dt == "U32") return 32;
  if (dt == "F16" || dt == "BF16" || dt == "I16" || dt == "U16") return 16;
  if (dt == "F8_E4M3" || dt == "F8_E5M2" || dt == "I8" || dt == "U8")
    return 8;
  if (dt == "BOOL") return 8;
  if (dt == "F4" || dt == "I4" || dt == "U4") return 4;
  return 16;  // conservative default
}

// ---- minimal JSON parser (objects/arrays/strings/numbers) ----------------

struct JsonParser {
  const char *p, *end;
  explicit JsonParser(const std::string &s)
      : p(s.data()), end(s.data() + s.size()) {}

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }
  bool consume(char c) {
    skip_ws();
    if (p < end && *p == c) {
      ++p;
      return true;
    }
    return false;
  }
  std::string parse_string() {
    skip_ws();
    std::string out;
    if (p >= end || *p != '"') return out;
    ++p;
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) {
        ++p;
        switch (*p) {
          case 'n': out += '\n'; break;
          case 't': out += '\t'; break;
          default: out += *p;
        }
      } else {
        out += *p;
      }
      ++p;
    }
    if (p < end) ++p;  // closing quote
    return out;
  }
  double parse_number() {
    skip_ws();
    char *np = nullptr;
    double v = strtod(p, &np);
    p = np;
    return v;
  }
  // skip any value (used for fields we don't care about)
  void skip_value() {
    skip_ws();
    if (p >= end) return;
    if (*p == '"') {
      parse_string();
    } else if (*p == '{') {
      ++p;
      skip_ws();
      if (consume('}')) return;
      do {
        parse_string();
        consume(':');
        skip_value();
      } while (consume(','));
      consume('}');
    } else if (*p == '[') {
      ++p;
      skip_ws();
      if (consume(']')) return;
      do {
        skip_value();
      } while (consume(','));
      consume(']');
    } else {
      // number / true / false / null
      while (p < end && *p != ',' && *p != '}' && *p != ']') ++p;
    }
  }
};

// ---- safetensors ---------------------------------------------------------

bool parse_safetensors(const std::string &path,
                       std::vector<TensorInfo> &tensors) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return false;
  uint64_t header_len = 0;
  f.read(reinterpret_cast<char *>(&header_len), 8);
  if (!f || header_len == 0 || header_len > (1ull << 31)) return false;
  std::string header(header_len, '\0');
  f.read(header.data(), header_len);
  if (!f) return false;

  JsonParser jp(header);
  if (!jp.consume('{')) return false;
  if (jp.consume('}')) return true;
  do {
    std::string name = jp.parse_string();
    jp.consume(':');
    if (name == "__metadata__") {
      jp.skip_value();
      continue;
    }
    TensorInfo ti;
    ti.name = name;
    if (!jp.consume('{')) return false;
    if (!jp.consume('}')) {
      do {
        std::string key = jp.parse_string();
        jp.consume(':');
        if (key == "dtype") {
          ti.dtype = jp.parse_string();
        } else if (key == "shape") {
          jp.consume('[');
          jp.skip_ws();
          if (*jp.p != ']') {
            do {
              ti.shape.push_back(
                  static_cast<int64_t>(jp.parse_number()));
            } while (jp.consume(','));
          }
          jp.consume(']');
        } else if (key == "data_offsets") {
          jp.consume('[');
          int64_t begin = static_cast<int64_t>(jp.parse_number());
          jp.consume(',');
          int64_t fin = static_cast<int64_t>(jp.parse_number());
          jp.consume(']');
          ti.bytes = fin - begin;
        } else {
          jp.skip_value();
        }
      } while (jp.consume(','));
      jp.consume('}');
    }
    if (ti.bytes == 0 && !ti.shape.empty()) {
      int64_t n = 1;
      for (int64_t d : ti.shape) n *= d;
      ti.bytes = n * dtype_bits(ti.dtype) / 8;
    }
    tensors.push_back(std::move(ti));
  } while (jp.consume(','));
  return true;
}

// ---- gguf (metadata header only) ----------------------------------------

struct GGUFReader {
  std::ifstream f;
  template <typename T> T rd() {
    T v{};
    f.read(reinterpret_cast<char *>(&v), sizeof(T));
    return v;
  }
  std::string rd_str() {
    uint64_t n = rd<uint64_t>();
    if (n > (1u << 20)) return "";
    std::string s(n, '\0');
    f.read(s.data(), n);
    return s;
  }
  void skip_value(uint32_t type);
};

void GGUFReader::skip_value(uint32_t type) {
  switch (type) {
    case 0: case 1: case 7: f.seekg(1, std::ios::cur); break;   // u8/i8/bool
    case 2: case 3: f.seekg(2, std::ios::cur); break;           // u16/i16
    case 4: case 5: case 6: f.seekg(4, std::ios::cur); break;   // u32/i32/f32
    case 10: case 11: case 12: f.seekg(8, std::ios::cur); break;// u64/i64/f64
    case 8: rd_str(); break;                                    // string
    case 9: {                                                   // array
      uint32_t elem_type = rd<uint32_t>();
      uint64_t count = rd<uint64_t>();
      for (uint64_t i = 0; i < count && f; ++i) skip_value(elem_type);
      break;
    }
    default: f.setstate(std::ios::failbit);
  }
}

// bits per element for common ggml quant types (id -> (bits, block))
double gguf_type_bits(uint32_t t) {
  switch (t) {
    case 0: return 32;      // F32
    case 1: return 16;      // F16
    case 2: return 4.5;     // Q4_0
    case 3: return 5;       // Q4_1
    case 6: return 5.5;     // Q5_0
    case 7: return 6;       // Q5_1
    case 8: return 8.5;     // Q8_0
    case 10: return 2.56;   // Q2_K
    case 11: return 3.44;   // Q3_K
    case 12: return 4.5;    // Q4_K
    case 13: return 5.5;    // Q5_K
    case 14: return 6.56;   // Q6_K
    case 16: return 2.06;   // IQ2_XXS
    case 30: return 16;     // BF16
    default: return 8;
  }
}

bool parse_gguf(const std::string &path, std::vector<TensorInfo> &tensors) {
  GGUFReader r;
  r.f.open(path, std::ios::binary);
  if (!r.f) return false;
  char magic[4];
  r.f.read(magic, 4);
  if (memcmp(magic, "GGUF", 4) != 0) return false;
  uint32_t version = r.rd<uint32_t>();
  if (version < 2 || version > 3) return false;
  uint64_t n_tensors = r.rd<uint64_t>();
  uint64_t n_kv = r.rd<uint64_t>();
  for (uint64_t i = 0; i < n_kv && r.f; ++i) {
    r.rd_str();                       // key
    uint32_t type = r.rd<uint32_t>();
    r.skip_value(type);
  }
  for (uint64_t i = 0; i < n_tensors && r.f; ++i) {
    TensorInfo ti;
    ti.name = r.rd_str();
    uint32_t ndim = r.rd<uint32_t>();
    int64_t n = 1;
    for (uint32_t d = 0; d < ndim; ++d) {
      int64_t dim = r.rd<uint64_t>();
      ti.shape.push_back(dim);
      n *= dim;
    }
    uint32_t type = r.rd<uint32_t>();
    r.rd<uint64_t>();                 // offset
    ti.dtype = "ggml_" + std::to_string(type);
    ti.bytes = static_cast<int64_t>(n * gguf_type_bits(type) / 8.0);
    tensors.push_back(std::move(ti));
  }
  return r.f.good();
}

// ---- aggregation ---------------------------------------------------------

int64_t param_count(const TensorInfo &t) {
  int64_t n = 1;
  for (int64_t d : t.shape) n *= d;
  return n;
}

// "model.layers.17.self_attn.q_proj.weight" -> 17, else -1
int layer_index(const std::string &name) {
  static const std::regex re(R"((?:^|\.)(?:layers|blk|h)\.(\d+)\.)");
  std::smatch m;
  if (std::regex_search(name, m, re)) return std::stoi(m[1]);
  return -1;
}

std::string json_escape(const std::string &s) {
  std::string out;
  for (char c : s) {
    if (c == '"' || c == '\\') out += '\\';
    out += c;
  }
  return out;
}

}  // namespace

int main(int argc, char **argv) {
  if (argc != 2) {
    fprintf(stderr, "usage: model-meta <model_dir|file>\n");
    return 2;
  }
  std::string arg = argv[1];
  std::vector<std::string> files;
  struct stat st{};
  if (stat(arg.c_str(), &st) != 0) {
    fprintf(stderr, "model-meta: cannot stat %s\n", arg.c_str());
    return 2;
  }
  if (S_ISDIR(st.st_mode)) {
    DIR *d = opendir(arg.c_str());
    if (!d) return 2;
    while (dirent *e = readdir(d)) {
      std::string n = e->d_name;
      if (n.size() > 12 &&
          n.compare(n.size() - 12, 12, ".safetensors") == 0)
        files.push_back(arg + "/" + n);
      else if (n.size() > 5 && n.compare(n.size() - 5, 5, ".gguf") == 0)
        files.push_back(arg + "/" + n);
    }
    closedir(d);
  } else {
    files.push_back(arg);
  }
  if (files.empty()) {
    fprintf(stderr, "model-meta: no checkpoint files in %s\n", arg.c_str());
    return 1;
  }

  std::string format;
  std::vector<TensorInfo> tensors;
  for (const std::string &f : files) {
    bool ok;
    if (f.size() > 5 && f.compare(f.size() - 5, 5, ".gguf") == 0) {
      ok = parse_gguf(f, tensors);
      format = "gguf";
    } else {
      ok = parse_safetensors(f, tensors);
      format = format.empty() ? "safetensors" : format;
    }
    if (!ok) {
      fprintf(stderr, "model-meta: failed to parse %s\n", f.c_str());
      return 1;
    }
  }

  int64_t total_bytes = 0, params = 0;
  std::map<std::string, int64_t> by_dtype;
  std::map<int, int64_t> by_layer;
  int64_t non_layer_bytes = 0;
  for (const auto &t : tensors) {
    total_bytes += t.bytes;
    params += param_count(t);
    by_dtype[t.dtype] += t.bytes;
    int li = layer_index(t.name);
    if (li >= 0)
      by_layer[li] += t.bytes;
    else
      non_layer_bytes += t.bytes;
  }
  int64_t max_layer = 0;
  for (auto &kv : by_layer) max_layer = std::max(max_layer, kv.second);

  printf("{\"format\": \"%s\", \"files\": %zu, \"tensors\": %zu, "
         "\"total_bytes\": %lld, \"params\": %lld, \"layers\": %zu, "
         "\"max_layer_bytes\": %lld, \"non_layer_bytes\": %lld, "
         "\"bytes_by_dtype\": {",
         format.c_str(), files.size(), tensors.size(),
         static_cast<long long>(total_bytes),
         static_cast<long long>(params), by_layer.size(),
         static_cast<long long>(max_layer),
         static_cast<long long>(non_layer_bytes));
  bool first = true;
  for (auto &kv : by_dtype) {
    printf("%s\"%s\": %lld", first ? "" : ", ",
           json_escape(kv.first).c_str(),
           static_cast<long long>(kv.second));
    first = false;
  }
  printf("}}\n");
  return 0;
}
