// sysinfo: host system probe with a stable JSON contract.
//
// Replaces the reference's fastfetch binary dependency (reference
// gpustack/detectors/fastfetch/fastfetch.py wraps a downloaded C binary
// for OS/CPU/memory/kernel detection; worker/tools_manager.py:19 fetches
// it). Zero dependencies: reads /proc and uname directly.
//
// Output: one JSON object on stdout:
//   {"hostname": ..., "os": ..., "kernel": ..., "arch": ...,
//    "cpu_count": N, "cpu_model": ..., "memory_total_bytes": N,
//    "memory_available_bytes": N, "uptime_seconds": N,
//    "tpu_devices": N, "tpu_accelerator_type": ..., "tpu_topology": ...}

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <dirent.h>
#include <fstream>
#include <string>
#include <sys/utsname.h>
#include <thread>
#include <unistd.h>

namespace {

std::string json_escape(const std::string &s) {
  std::string out;
  for (char c : s) {
    if (c == '"' || c == '\\') out += '\\';
    if (c >= 0x20 || c == '\t') out += c;
  }
  return out;
}

long long meminfo_kb(const char *key) {
  std::ifstream f("/proc/meminfo");
  std::string line;
  size_t keylen = strlen(key);
  while (std::getline(f, line)) {
    if (line.compare(0, keylen, key) == 0 && line[keylen] == ':') {
      return atoll(line.c_str() + keylen + 1);
    }
  }
  return 0;
}

std::string cpu_model() {
  std::ifstream f("/proc/cpuinfo");
  std::string line;
  while (std::getline(f, line)) {
    if (line.compare(0, 10, "model name") == 0) {
      size_t pos = line.find(':');
      if (pos != std::string::npos) {
        size_t start = line.find_first_not_of(" \t", pos + 1);
        return start == std::string::npos ? "" : line.substr(start);
      }
    }
  }
  return "";
}

double uptime_seconds() {
  std::ifstream f("/proc/uptime");
  double up = 0;
  f >> up;
  return up;
}

int count_tpu_devices() {
  int n = 0;
  if (DIR *d = opendir("/dev")) {
    while (dirent *e = readdir(d)) {
      if (strncmp(e->d_name, "accel", 5) == 0) ++n;
    }
    closedir(d);
  }
  return n;
}

std::string getenv_str(const char *name) {
  const char *v = getenv(name);
  return v ? v : "";
}

}  // namespace

int main() {
  utsname uts{};
  uname(&uts);
  char hostname[256] = {0};
  gethostname(hostname, sizeof(hostname) - 1);

  printf(
      "{\"hostname\": \"%s\", \"os\": \"%s\", \"kernel\": \"%s\", "
      "\"arch\": \"%s\", \"cpu_count\": %u, \"cpu_model\": \"%s\", "
      "\"memory_total_bytes\": %lld, \"memory_available_bytes\": %lld, "
      "\"uptime_seconds\": %.0f, \"tpu_devices\": %d, "
      "\"tpu_accelerator_type\": \"%s\", \"tpu_topology\": \"%s\"}\n",
      json_escape(hostname).c_str(), json_escape(uts.sysname).c_str(),
      json_escape(uts.release).c_str(), json_escape(uts.machine).c_str(),
      std::thread::hardware_concurrency(),
      json_escape(cpu_model()).c_str(), meminfo_kb("MemTotal") * 1024,
      meminfo_kb("MemAvailable") * 1024, uptime_seconds(),
      count_tpu_devices(),
      json_escape(getenv_str("TPU_ACCELERATOR_TYPE")).c_str(),
      json_escape(getenv_str("TPU_TOPOLOGY")).c_str());
  return 0;
}
